// ray_tpu C++ client API.
//
// Reference analogue: cpp/src/ray/api.cc — a non-Python driver for the
// cluster. This client speaks the framed-msgpack control protocol
// (ray_tpu/_private/protocol.py: [uint32 len][msgpack [type, seq,
// method, payload]]) against the ray:// client server
// (ray_tpu/util/client/server.py), using the raw (pickle-free) surface:
// values are native msgpack, tasks are invoked by cross_language
// registry name. Single-threaded synchronous calls; no external
// dependencies (the msgpack subset codec is below).
//
// Usage:
//   ray::Client c("127.0.0.1", 10001);
//   auto ref = c.CallNamed("math.add", {ray::Value::Int(1),
//                                       ray::Value::Int(41)});
//   int64_t v = c.Get(ref).AsInt();             // 42
//   auto oref = c.Put(ray::Value::Str("hello"));
//   c.KvPut("key", "val");  c.KvGet("key");
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

namespace ray {

// ------------------------------------------------------------------ Value
// A dynamic msgpack value (nil/bool/int/float/str/bin/array/map).

struct Value {
  enum class Kind { Nil, Bool, Int, Float, Str, Bin, Array, Map };
  Kind kind = Kind::Nil;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;  // Str and Bin payloads
  std::vector<Value> arr;
  std::vector<std::pair<Value, Value>> map;

  static Value Nil() { return Value{}; }
  static Value Boolean(bool v) { Value x; x.kind = Kind::Bool; x.b = v; return x; }
  static Value Int(int64_t v) { Value x; x.kind = Kind::Int; x.i = v; return x; }
  static Value Float(double v) { Value x; x.kind = Kind::Float; x.f = v; return x; }
  static Value Str(std::string v) { Value x; x.kind = Kind::Str; x.s = std::move(v); return x; }
  static Value Bin(std::string v) { Value x; x.kind = Kind::Bin; x.s = std::move(v); return x; }
  static Value Array(std::vector<Value> v) { Value x; x.kind = Kind::Array; x.arr = std::move(v); return x; }
  static Value MapV(std::vector<std::pair<Value, Value>> v) { Value x; x.kind = Kind::Map; x.map = std::move(v); return x; }

  bool IsNil() const { return kind == Kind::Nil; }
  int64_t AsInt() const {
    if (kind == Kind::Int) return i;
    if (kind == Kind::Float) return static_cast<int64_t>(f);
    throw std::runtime_error("Value is not an int");
  }
  double AsFloat() const {
    if (kind == Kind::Float) return f;
    if (kind == Kind::Int) return static_cast<double>(i);
    throw std::runtime_error("Value is not a float");
  }
  const std::string& AsStr() const {
    if (kind != Kind::Str && kind != Kind::Bin)
      throw std::runtime_error("Value is not a string");
    return s;
  }
  const Value* MapGet(const std::string& key) const {
    for (const auto& kv : map)
      if (kv.first.kind == Kind::Str && kv.first.s == key) return &kv.second;
    return nullptr;
  }
};

// ---------------------------------------------------------------- msgpack

namespace mp {

inline void PutByte(std::string& out, uint8_t b) { out.push_back(static_cast<char>(b)); }
// value-based big-endian writes: independent of host byte order
inline void PutBE16(std::string& out, uint16_t x) {
  PutByte(out, static_cast<uint8_t>(x >> 8));
  PutByte(out, static_cast<uint8_t>(x));
}
inline void PutBE32(std::string& out, uint32_t x) {
  for (int k = 24; k >= 0; k -= 8) PutByte(out, static_cast<uint8_t>(x >> k));
}
inline void PutBE64(std::string& out, uint64_t x) {
  for (int k = 56; k >= 0; k -= 8) PutByte(out, static_cast<uint8_t>(x >> k));
}
inline void PutLen(std::string& out, size_t n, uint8_t t8, uint8_t t16,
                   uint8_t t32) {
  if (n < 256 && t8 != 0) { PutByte(out, t8); PutByte(out, static_cast<uint8_t>(n)); }
  else if (n < 65536) { PutByte(out, t16); PutBE16(out, static_cast<uint16_t>(n)); }
  else { PutByte(out, t32); PutBE32(out, static_cast<uint32_t>(n)); }
}

inline void Encode(const Value& v, std::string& out) {
  switch (v.kind) {
    case Value::Kind::Nil: PutByte(out, 0xc0); break;
    case Value::Kind::Bool: PutByte(out, v.b ? 0xc3 : 0xc2); break;
    case Value::Kind::Int: {
      int64_t x = v.i;
      if (x >= 0 && x < 128) { PutByte(out, static_cast<uint8_t>(x)); }
      else if (x < 0 && x >= -32) { PutByte(out, static_cast<uint8_t>(0xe0 | (x + 32))); }
      else { PutByte(out, 0xd3); PutBE64(out, static_cast<uint64_t>(x)); }
      break;
    }
    case Value::Kind::Float: {
      uint64_t bits;
      std::memcpy(&bits, &v.f, 8);
      PutByte(out, 0xcb);
      PutBE64(out, bits);
      break;
    }
    case Value::Kind::Str: {
      size_t n = v.s.size();
      if (n < 32) PutByte(out, static_cast<uint8_t>(0xa0 | n));
      else PutLen(out, n, 0xd9, 0xda, 0xdb);
      out += v.s;
      break;
    }
    case Value::Kind::Bin: {
      PutLen(out, v.s.size(), 0xc4, 0xc5, 0xc6);
      out += v.s;
      break;
    }
    case Value::Kind::Array: {
      size_t n = v.arr.size();
      if (n < 16) PutByte(out, static_cast<uint8_t>(0x90 | n));
      else PutLen(out, n, 0, 0xdc, 0xdd);
      for (const auto& e : v.arr) Encode(e, out);
      break;
    }
    case Value::Kind::Map: {
      size_t n = v.map.size();
      if (n < 16) PutByte(out, static_cast<uint8_t>(0x80 | n));
      else PutLen(out, n, 0, 0xde, 0xdf);
      for (const auto& kv : v.map) { Encode(kv.first, out); Encode(kv.second, out); }
      break;
    }
  }
}

struct Reader {
  const uint8_t* p;
  const uint8_t* end;
  uint8_t Byte() {
    if (p >= end) throw std::runtime_error("msgpack: truncated");
    return *p++;
  }
  void Bytes(void* dst, size_t n) {
    if (p + n > end) throw std::runtime_error("msgpack: truncated");
    std::memcpy(dst, p, n);
    p += n;
  }
  uint64_t BE(size_t n) {
    uint64_t x = 0;
    for (size_t k = 0; k < n; ++k) x = (x << 8) | Byte();
    return x;
  }
  std::string Raw(size_t n) {
    if (p + n > end) throw std::runtime_error("msgpack: truncated");
    std::string s(reinterpret_cast<const char*>(p), n);
    p += n;
    return s;
  }
};

inline Value Decode(Reader& r) {
  uint8_t t = r.Byte();
  if (t < 0x80) return Value::Int(t);
  if (t >= 0xe0) return Value::Int(static_cast<int8_t>(t));
  if ((t & 0xf0) == 0x90 || t == 0xdc || t == 0xdd) {
    size_t n = (t == 0xdc) ? r.BE(2) : (t == 0xdd) ? r.BE(4) : (t & 0x0f);
    std::vector<Value> a;
    a.reserve(n);
    for (size_t k = 0; k < n; ++k) a.push_back(Decode(r));
    return Value::Array(std::move(a));
  }
  if ((t & 0xf0) == 0x80 || t == 0xde || t == 0xdf) {
    size_t n = (t == 0xde) ? r.BE(2) : (t == 0xdf) ? r.BE(4) : (t & 0x0f);
    std::vector<std::pair<Value, Value>> m;
    m.reserve(n);
    for (size_t k = 0; k < n; ++k) {
      Value key = Decode(r);
      Value val = Decode(r);
      m.emplace_back(std::move(key), std::move(val));
    }
    return Value::MapV(std::move(m));
  }
  if ((t & 0xe0) == 0xa0) return Value::Str(r.Raw(t & 0x1f));
  switch (t) {
    case 0xc0: return Value::Nil();
    case 0xc2: return Value::Boolean(false);
    case 0xc3: return Value::Boolean(true);
    case 0xc4: return Value::Bin(r.Raw(r.BE(1)));
    case 0xc5: return Value::Bin(r.Raw(r.BE(2)));
    case 0xc6: return Value::Bin(r.Raw(r.BE(4)));
    case 0xca: { uint32_t x = static_cast<uint32_t>(r.BE(4)); float f;
                 std::memcpy(&f, &x, 4); return Value::Float(f); }
    case 0xcb: { uint64_t x = r.BE(8); double d; std::memcpy(&d, &x, 8);
                 return Value::Float(d); }
    case 0xcc: return Value::Int(static_cast<int64_t>(r.BE(1)));
    case 0xcd: return Value::Int(static_cast<int64_t>(r.BE(2)));
    case 0xce: return Value::Int(static_cast<int64_t>(r.BE(4)));
    case 0xcf: return Value::Int(static_cast<int64_t>(r.BE(8)));
    case 0xd0: return Value::Int(static_cast<int8_t>(r.BE(1)));
    case 0xd1: return Value::Int(static_cast<int16_t>(r.BE(2)));
    case 0xd2: return Value::Int(static_cast<int32_t>(r.BE(4)));
    case 0xd3: return Value::Int(static_cast<int64_t>(r.BE(8)));
    case 0xd9: return Value::Str(r.Raw(r.BE(1)));
    case 0xda: return Value::Str(r.Raw(r.BE(2)));
    case 0xdb: return Value::Str(r.Raw(r.BE(4)));
    default:
      throw std::runtime_error("msgpack: unsupported type byte");
  }
}

}  // namespace mp

// ---------------------------------------------------------------- Client

class ObjectRef {
 public:
  explicit ObjectRef(std::string hex = "") : hex_(std::move(hex)) {}
  const std::string& Hex() const { return hex_; }

 private:
  std::string hex_;
};

class Client {
 public:
  Client(const std::string& host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd_ < 0) throw std::runtime_error("socket() failed");
    int one = 1;
    ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      throw std::runtime_error("bad host " + host);
    if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
      throw std::runtime_error("connect() failed");
    Value hello = Call("client_hello",
                       {{Value::Str("namespace"), Value::Str("")}});
    (void)hello;
  }
  ~Client() {
    if (fd_ >= 0) ::close(fd_);
  }
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  ObjectRef Put(const Value& v) {
    Value r = Call("client_put_raw", {{Value::Str("value"), v}});
    return ObjectRef(r.AsStr());
  }

  Value Get(const ObjectRef& ref, double timeout_s = 60.0) {
    Value r = Call("client_get_raw",
                   {{Value::Str("ids"),
                     Value::Array({Value::Str(ref.Hex())})},
                    {Value::Str("timeout"), Value::Float(timeout_s)}});
    const Value& item = r.arr.at(0);
    const Value* err = item.MapGet("error");
    if (err != nullptr && !err->IsNil())
      throw std::runtime_error("remote error: " + err->AsStr());
    const Value* val = item.MapGet("value");
    return val == nullptr ? Value::Nil() : *val;
  }

  // Invoke a Python function registered via
  // ray_tpu.util.cross_language.register_function(name, fn). The server
  // replies with the list of return refs; single-return calls get one.
  std::vector<ObjectRef> CallNamedMulti(const std::string& name,
                                        std::vector<Value> args) {
    Value r = Call("client_call_named",
                   {{Value::Str("name"), Value::Str(name)},
                    {Value::Str("args"), Value::Array(std::move(args))}});
    std::vector<ObjectRef> out;
    for (const auto& h : r.arr) out.emplace_back(h.AsStr());
    return out;
  }

  ObjectRef CallNamed(const std::string& name, std::vector<Value> args) {
    auto refs = CallNamedMulti(name, std::move(args));
    if (refs.empty()) throw std::runtime_error("no return ref");
    return refs.front();
  }

  // Drop the server-side pin for a ref this client no longer needs
  // (fire-and-forget; the table otherwise holds it until disconnect).
  void Release(const ObjectRef& ref) {
    Notify("client_release",
           {{Value::Str("ids"),
             Value::Array({Value::Str(ref.Hex())})}});
  }

  std::vector<std::string> ListNamed() {
    Value r = Call("client_list_named",
                   std::vector<std::pair<Value, Value>>{});
    std::vector<std::string> out;
    for (const auto& v : r.arr) out.push_back(v.AsStr());
    return out;
  }

  void KvPut(const std::string& key, const std::string& value) {
    Call("client_kv", {{Value::Str("op"), Value::Str("put")},
                       {Value::Str("key"), Value::Str(key)},
                       {Value::Str("value"), Value::Bin(value)}});
  }

  std::string KvGet(const std::string& key) {
    Value r = Call("client_kv", {{Value::Str("op"), Value::Str("get")},
                                 {Value::Str("key"), Value::Str(key)}});
    return r.IsNil() ? std::string() : r.AsStr();
  }

  Value ClusterResources() {
    return Call("client_cluster_info",
                {{Value::Str("kind"), Value::Str("cluster_resources")}});
  }

  // One framed request/reply round-trip (msg types per protocol.py:
  // 0=request, 1=reply, 2=error, 3=notify).
  Value Call(const std::string& method,
             std::vector<std::pair<Value, Value>> payload) {
    int64_t seq = ++seq_;
    SendFrame(Value::Array({Value::Int(0), Value::Int(seq),
                            Value::Str(method),
                            Value::MapV(std::move(payload))}));
    for (;;) {
      Value msg = ReadFrame();
      int64_t mtype = msg.arr.at(0).AsInt();
      int64_t mseq = msg.arr.at(1).AsInt();
      if (mseq != seq) continue;  // single-threaded: stale replies only
      if (mtype == 2)
        throw std::runtime_error("rpc error: " + msg.arr.at(3).AsStr());
      return msg.arr.at(3);
    }
  }

  void Notify(const std::string& method,
              std::vector<std::pair<Value, Value>> payload) {
    SendFrame(Value::Array({Value::Int(3), Value::Nil(),
                            Value::Str(method),
                            Value::MapV(std::move(payload))}));
  }

 private:
  void SendFrame(const Value& body) {
    std::string data;
    mp::Encode(body, data);
    // protocol.py frames with little-endian "<I"
    uint32_t n = static_cast<uint32_t>(data.size());
    std::string frame;
    for (int k = 0; k < 32; k += 8)
      frame.push_back(static_cast<char>((n >> k) & 0xff));
    frame += data;
    SendAll(frame.data(), frame.size());
  }

  void SendAll(const char* p, size_t n) {
    while (n > 0) {
      ssize_t w = ::send(fd_, p, n, 0);
      if (w <= 0) throw std::runtime_error("send() failed");
      p += w;
      n -= static_cast<size_t>(w);
    }
  }
  void RecvAll(char* p, size_t n) {
    while (n > 0) {
      ssize_t r = ::recv(fd_, p, n, 0);
      if (r <= 0) throw std::runtime_error("connection closed");
      p += r;
      n -= static_cast<size_t>(r);
    }
  }
  Value ReadFrame() {
    uint8_t hdr[4];
    RecvAll(reinterpret_cast<char*>(hdr), 4);
    uint32_t n = static_cast<uint32_t>(hdr[0]) |
                 (static_cast<uint32_t>(hdr[1]) << 8) |
                 (static_cast<uint32_t>(hdr[2]) << 16) |
                 (static_cast<uint32_t>(hdr[3]) << 24);
    std::string buf(n, '\0');
    RecvAll(buf.data(), n);
    mp::Reader r{reinterpret_cast<const uint8_t*>(buf.data()),
                 reinterpret_cast<const uint8_t*>(buf.data()) + n};
    return mp::Decode(r);
  }

  int fd_ = -1;
  int64_t seq_ = 0;
};

}  // namespace ray
