// End-to-end smoke driver for the C++ client API (built and run by
// tests/test_cpp_client.py against a live cluster + client server).
// Exits 0 on success; prints the failing step otherwise.
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "ray_tpu_client.hpp"

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s <port>\n", argv[0]);
    return 2;
  }
  try {
    ray::Client c("127.0.0.1", std::atoi(argv[1]));

    // put/get round-trips across types
    auto r1 = c.Put(ray::Value::Int(12345));
    if (c.Get(r1).AsInt() != 12345) { std::puts("FAIL int"); return 1; }
    auto r2 = c.Put(ray::Value::Str("hello from c++"));
    if (c.Get(r2).AsStr() != "hello from c++") {
      std::puts("FAIL str");
      return 1;
    }
    auto r3 = c.Put(ray::Value::Array(
        {ray::Value::Int(1), ray::Value::Float(2.5),
         ray::Value::Str("x")}));
    auto v3 = c.Get(r3);
    if (v3.arr.size() != 3 || v3.arr[1].AsFloat() != 2.5) {
      std::puts("FAIL array");
      return 1;
    }

    // cross-language task invocation by registered name
    auto names = c.ListNamed();
    bool found = false;
    for (const auto& n : names) found = found || n == "math.add";
    if (!found) { std::puts("FAIL list_named"); return 1; }
    auto rr = c.CallNamed("math.add",
                          {ray::Value::Int(1), ray::Value::Int(41)});
    if (c.Get(rr).AsInt() != 42) { std::puts("FAIL call_named"); return 1; }
    // chain: pass a fetched value back into another call
    auto rs = c.CallNamed("str.concat", {ray::Value::Str("tpu-"),
                                         ray::Value::Str("native")});
    if (c.Get(rs).AsStr() != "tpu-native") {
      std::puts("FAIL concat");
      return 1;
    }

    // error propagation
    bool threw = false;
    try {
      auto rb = c.CallNamed("math.boom", {});
      c.Get(rb);
    } catch (const std::exception& e) {
      threw = std::strstr(e.what(), "kaboom") != nullptr;
    }
    if (!threw) { std::puts("FAIL error-propagation"); return 1; }

    // large payloads exercise the str32 encode path (>64 KiB)
    std::string big(100000, 'x');
    auto rbig = c.Put(ray::Value::Str(big));
    if (c.Get(rbig).AsStr() != big) { std::puts("FAIL big-str"); return 1; }
    c.Release(rbig);
    c.Release(r1);
    // the connection must still be healthy after notifies
    if (c.Get(r2).AsStr() != "hello from c++") {
      std::puts("FAIL post-release");
      return 1;
    }

    // kv + cluster info
    c.KvPut("cpp/key", "cpp-value");
    if (c.KvGet("cpp/key") != "cpp-value") { std::puts("FAIL kv"); return 1; }
    auto res = c.ClusterResources();
    const ray::Value* cpu = res.MapGet("CPU");
    if (cpu == nullptr || cpu->AsFloat() < 1.0) {
      std::puts("FAIL cluster_resources");
      return 1;
    }

    std::puts("CPP_CLIENT_OK");
    return 0;
  } catch (const std::exception& e) {
    std::fprintf(stderr, "FAIL exception: %s\n", e.what());
    return 1;
  }
}
