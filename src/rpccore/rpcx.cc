// rpccore: the native RPC frame pump (ROADMAP item 2 / docs/WIRE_PROTOCOL.md
// "Implementations").
//
// Owns sockets speaking the ray_tpu control-plane framing —
// [uint32_le length][msgpack body] — and moves the length-prefixed
// read / partial-write / coalesced-send loops out of Python
// (_private/protocol.py asyncio handlers). msgpack encode/decode stays in
// Python: the pump's contract is BYTES (frame boundaries), which is what
// keeps it byte-identical to the Python implementation by construction —
// both sides of every frame are produced by the same msgpack library.
//
// Design: a reactor with NO threads of its own. The caller's thread drives
// it through rpcx_next_batch (epoll_wait + reads + frame parsing run there,
// with the GIL released by ctypes), which is what lets the worker's
// direct-execution lane run recv -> decode -> execute -> reply on ONE
// thread (ray_tpu/_private/direct.py). Sends may come from ANY thread:
// they write straight to the fd under a per-connection mutex (partial
// writes looped with poll), so a reply never waits on the reactor.
//
// Role-equivalent to the reference's gRPC C-core event engine
// (reference: src/ray/rpc/ client_call.h / grpc_server.cc) at the scale
// this runtime needs: one pump per process role, O(10) connections.
//
// Built like src/plasmax and src/schedcore:
//   g++ -O2 -fPIC -shared -o ray_tpu/core/librpcx.so src/rpccore/rpcx.cc
// (ray_tpu/_private/rpccore.py builds it on demand and falls back to the
// pure-Python path when the build or load fails.)

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <sys/un.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMaxFrame = 256u * 1024u * 1024u;  // protocol._MAX_FRAME
constexpr int kReadChunk = 256 * 1024;

// event kinds delivered to Python
constexpr int kKindFrame = 1;
constexpr int kKindClosed = 2;
constexpr int kKindWake = 3;  // rpcx_wake: a thread wants the reactor

struct Conn {
  int fd = -1;
  long id = 0;
  bool closed = false;           // fd shut; send() refuses
  std::vector<uint8_t> rbuf;     // unparsed inbound bytes
  size_t rhead = 0;              // parse cursor into rbuf
  std::mutex wmu;                // serializes writers (coalesces under
                                 // contention: later senders append while
                                 // an earlier writev is in flight)
};

struct Event {
  long cid = 0;
  int kind = 0;
  uint8_t* data = nullptr;  // malloc'd frame body (caller frees)
  uint32_t len = 0;
};

struct Pump {
  int ep = -1;
  int wake_fd = -1;
  int listen_fd = -1;      // AF_UNIX listener (tag UINT64_MAX - 1)
  int listen_fd_tcp = -1;  // AF_INET listener (tag UINT64_MAX - 2)
  std::mutex mu;  // conns map + event queue + ids
  std::unordered_map<long, Conn*> conns;
  std::deque<Event> q;
  long next_id = 1;
  std::atomic<bool> shutdown{false};
  std::mutex reactor_mu;  // at most one thread inside epoll_wait
  // stats (indexes documented at rpcx_stats)
  std::atomic<uint64_t> frames_in{0}, frames_out{0};
  std::atomic<uint64_t> bytes_in{0}, bytes_out{0};
  std::atomic<uint64_t> read_calls{0}, write_calls{0};
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

int64_t now_ms() {
  struct timeval tv;
  gettimeofday(&tv, nullptr);
  return int64_t(tv.tv_sec) * 1000 + tv.tv_usec / 1000;
}

// TCP fast-path socket options; silently no-ops on AF_UNIX fds (the
// setsockopt fails with EOPNOTSUPP and we don't care)
void set_tcp_opts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
}

Conn* add_conn(Pump* p, int fd) {
  set_nonblock(fd);
  set_tcp_opts(fd);
  auto* c = new Conn();
  c->fd = fd;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    c->id = p->next_id++;
    p->conns[c->id] = c;
  }
  struct epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  ev.events = EPOLLIN;
  ev.data.u64 = static_cast<uint64_t>(c->id);
  epoll_ctl(p->ep, EPOLL_CTL_ADD, fd, &ev);
  return c;
}

// mark closed + queue the close event; Conn structs live until pump
// shutdown (a send racing the close must find a poisoned conn, not freed
// memory — connection churn here is lease-lifetime, not per-request)
void close_conn_locked(Pump* p, Conn* c) {
  if (c->closed) return;
  c->closed = true;
  epoll_ctl(p->ep, EPOLL_CTL_DEL, c->fd, nullptr);
  ::close(c->fd);
  Event e;
  e.cid = c->id;
  e.kind = kKindClosed;
  p->q.push_back(e);
}

// parse complete frames out of c->rbuf into the event queue
void parse_frames(Pump* p, Conn* c) {
  for (;;) {
    size_t avail = c->rbuf.size() - c->rhead;
    if (avail < 4) break;
    const uint8_t* base = c->rbuf.data() + c->rhead;
    uint32_t n;
    std::memcpy(&n, base, 4);  // uint32 little-endian on every TPU host
    if (n > kMaxFrame) {  // protocol error, same as read_frame()
      std::lock_guard<std::mutex> lk(p->mu);
      close_conn_locked(p, c);
      return;
    }
    if (avail < 4u + n) break;
    auto* body = static_cast<uint8_t*>(std::malloc(n ? n : 1));
    std::memcpy(body, base + 4, n);
    c->rhead += 4u + n;
    Event e;
    e.cid = c->id;
    e.kind = kKindFrame;
    e.data = body;
    e.len = n;
    {
      std::lock_guard<std::mutex> lk(p->mu);
      p->q.push_back(e);
    }
    p->frames_in.fetch_add(1, std::memory_order_relaxed);
  }
  // compact once the parsed prefix dominates (keeps the buffer O(frame))
  if (c->rhead > 0 && c->rhead * 2 >= c->rbuf.size()) {
    c->rbuf.erase(c->rbuf.begin(), c->rbuf.begin() + c->rhead);
    c->rhead = 0;
  }
}

void drain_readable(Pump* p, Conn* c) {
  for (;;) {
    size_t old = c->rbuf.size();
    c->rbuf.resize(old + kReadChunk);
    ssize_t n = ::recv(c->fd, c->rbuf.data() + old, kReadChunk, 0);
    p->read_calls.fetch_add(1, std::memory_order_relaxed);
    if (n > 0) {
      c->rbuf.resize(old + n);
      p->bytes_in.fetch_add(n, std::memory_order_relaxed);
      parse_frames(p, c);
      if (c->closed) return;
      if (n < kReadChunk) return;  // drained
      continue;
    }
    c->rbuf.resize(old);
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) return;
    // EOF or hard error
    std::lock_guard<std::mutex> lk(p->mu);
    close_conn_locked(p, c);
    return;
  }
}

void accept_ready(Pump* p, int listen_fd) {
  for (;;) {
    int fd = ::accept4(listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
    if (fd < 0) return;
    add_conn(p, fd);
  }
}

// run the reactor once (events + reads); returns when something was
// enqueued or the timeout elapsed
void reactor_step(Pump* p, int timeout_ms) {
  struct epoll_event evs[64];
  int n = epoll_wait(p->ep, evs, 64, timeout_ms);
  for (int i = 0; i < n; i++) {
    uint64_t tag = evs[i].data.u64;
    if (tag == UINT64_MAX) {  // wake eventfd
      uint64_t buf;
      while (::read(p->wake_fd, &buf, 8) == 8) {
      }
      continue;
    }
    if (tag == UINT64_MAX - 1) {  // unix listener
      accept_ready(p, p->listen_fd);
      continue;
    }
    if (tag == UINT64_MAX - 2) {  // tcp listener
      accept_ready(p, p->listen_fd_tcp);
      continue;
    }
    Conn* c = nullptr;
    {
      std::lock_guard<std::mutex> lk(p->mu);
      auto it = p->conns.find(static_cast<long>(tag));
      if (it != p->conns.end() && !it->second->closed) c = it->second;
    }
    if (c == nullptr) continue;
    if (evs[i].events & (EPOLLHUP | EPOLLERR)) {
      // drain what the kernel still buffers, then close
      drain_readable(p, c);
      std::lock_guard<std::mutex> lk(p->mu);
      close_conn_locked(p, c);
      continue;
    }
    if (evs[i].events & EPOLLIN) drain_readable(p, c);
  }
}

}  // namespace

extern "C" {

// bumped on any signature/semantic change; the Python loader refuses a
// stale .so (a rebuilt checkout can otherwise load yesterday's binary)
int rpcx_abi_version() { return 4; }

void* rpcx_create() {
  auto* p = new Pump();
  p->ep = epoll_create1(EPOLL_CLOEXEC);
  p->wake_fd = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  struct epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  ev.events = EPOLLIN;
  ev.data.u64 = UINT64_MAX;
  epoll_ctl(p->ep, EPOLL_CTL_ADD, p->wake_fd, &ev);
  return p;
}

int rpcx_listen(void* vp, const char* path) {
  auto* p = static_cast<Pump*>(vp);
  int fd = ::socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) return -1;
  struct sockaddr_un addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  ::unlink(path);
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return -1;
  }
  set_nonblock(fd);
  p->listen_fd = fd;
  struct epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  ev.events = EPOLLIN;
  ev.data.u64 = UINT64_MAX - 1;
  epoll_ctl(p->ep, EPOLL_CTL_ADD, fd, &ev);
  return 0;
}

// TCP listener (netx off-box transport). Binds host:port (port 0 =
// ephemeral) and returns the BOUND port, or -1. Framing on accepted
// connections is byte-identical to the unix path — same parse_frames,
// same kMaxFrame — so the schema-1.7 conformance vectors run unchanged.
int rpcx_listen_tcp(void* vp, const char* host, int port) {
  auto* p = static_cast<Pump*>(vp);
  int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return -1;
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 128) < 0) {
    ::close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  if (::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen) < 0) {
    ::close(fd);
    return -1;
  }
  set_nonblock(fd);
  p->listen_fd_tcp = fd;
  struct epoll_event ev;
  std::memset(&ev, 0, sizeof(ev));
  ev.events = EPOLLIN;
  ev.data.u64 = UINT64_MAX - 2;
  epoll_ctl(p->ep, EPOLL_CTL_ADD, fd, &ev);
  return ntohs(addr.sin_port);
}

// Dial host:port. Hostname resolution via getaddrinfo (numeric IPs skip
// the resolver). Blocking connect, same as the unix dial — callers hold
// no pump lock while dialing.
long rpcx_dial_tcp(void* vp, const char* host, int port) {
  auto* p = static_cast<Pump*>(vp);
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  std::snprintf(portbuf, sizeof(portbuf), "%d", port);
  struct addrinfo* res = nullptr;
  if (::getaddrinfo(host, portbuf, &hints, &res) != 0 || res == nullptr)
    return -1;
  int fd = ::socket(res->ai_family, res->ai_socktype | SOCK_CLOEXEC,
                    res->ai_protocol);
  if (fd < 0) {
    ::freeaddrinfo(res);
    return -1;
  }
  int rc = ::connect(fd, res->ai_addr, res->ai_addrlen);
  ::freeaddrinfo(res);
  if (rc < 0) {
    ::close(fd);
    return -1;
  }
  Conn* c = add_conn(p, fd);
  uint64_t one = 1;
  ssize_t wrc = ::write(p->wake_fd, &one, 8);
  (void)wrc;
  return c->id;
}

long rpcx_dial(void* vp, const char* path) {
  auto* p = static_cast<Pump*>(vp);
  int fd = ::socket(AF_UNIX, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) return -1;
  struct sockaddr_un addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    ::close(fd);
    return -1;
  }
  Conn* c = add_conn(p, fd);
  // wake a parked reactor so the new fd joins its epoll set promptly
  uint64_t one = 1;
  ssize_t rc = ::write(p->wake_fd, &one, 8);
  (void)rc;
  return c->id;
}

// Pop up to `max` events. kinds[i]: 1=frame (datas/lens set), 2=conn
// closed. Returns the count, 0 on timeout, -1 after shutdown. Batching
// amortizes the C<->Python boundary when a socket read yielded several
// frames (pipelined leased tasks, coalesced peers).
int rpcx_next_batch(void* vp, long* cids, int* kinds, uint8_t** datas,
                    uint32_t* lens, int max, int timeout_ms) {
  auto* p = static_cast<Pump*>(vp);
  int64_t deadline = timeout_ms >= 0 ? now_ms() + timeout_ms : -1;
  for (;;) {
    {
      std::lock_guard<std::mutex> lk(p->mu);
      if (!p->q.empty()) {
        int n = 0;
        while (n < max && !p->q.empty()) {
          Event e = p->q.front();
          p->q.pop_front();
          cids[n] = e.cid;
          kinds[n] = e.kind;
          datas[n] = e.data;
          lens[n] = e.len;
          n++;
        }
        return n;
      }
    }
    if (p->shutdown.load()) return -1;
    int step;
    if (deadline < 0) {
      step = 200;  // re-check shutdown periodically even without timeout
    } else {
      int64_t left = deadline - now_ms();
      if (left <= 0) return 0;
      step = left > 200 ? 200 : static_cast<int>(left);
    }
    std::lock_guard<std::mutex> rk(p->reactor_mu);
    reactor_step(p, step);
  }
}

void rpcx_free(uint8_t* data) { std::free(data); }

// Send one frame: writes [uint32_le len][body]. Returns 0, or -1 when the
// connection is unknown/closed or the write fails. Partial writes loop
// with poll (the "partial-write loop" that used to live in asyncio's
// transport); concurrent senders serialize on the conn mutex, so bodies
// from racing threads interleave at frame granularity only.
int rpcx_send(void* vp, long cid, const uint8_t* body, uint32_t len) {
  auto* p = static_cast<Pump*>(vp);
  if (len > kMaxFrame) return -1;
  Conn* c = nullptr;
  {
    std::lock_guard<std::mutex> lk(p->mu);
    auto it = p->conns.find(cid);
    if (it == p->conns.end()) return -1;
    c = it->second;
  }
  std::lock_guard<std::mutex> wk(c->wmu);
  if (c->closed) return -1;
  uint8_t hdr[4];
  std::memcpy(hdr, &len, 4);
  struct iovec iov[2];
  iov[0].iov_base = hdr;
  iov[0].iov_len = 4;
  iov[1].iov_base = const_cast<uint8_t*>(body);
  iov[1].iov_len = len;
  size_t total = 4u + len, sent = 0;
  while (sent < total) {
    struct msghdr mh;
    std::memset(&mh, 0, sizeof(mh));
    // advance the iovec past what's already on the wire
    struct iovec cur[2];
    int niov = 0;
    size_t skip = sent;
    for (int i = 0; i < 2; i++) {
      if (skip >= iov[i].iov_len) {
        skip -= iov[i].iov_len;
        continue;
      }
      cur[niov].iov_base = static_cast<uint8_t*>(iov[i].iov_base) + skip;
      cur[niov].iov_len = iov[i].iov_len - skip;
      skip = 0;
      niov++;
    }
    mh.msg_iov = cur;
    mh.msg_iovlen = niov;
    ssize_t n = ::sendmsg(c->fd, &mh, MSG_NOSIGNAL);
    p->write_calls.fetch_add(1, std::memory_order_relaxed);
    if (n > 0) {
      sent += static_cast<size_t>(n);
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      struct pollfd pf;
      pf.fd = c->fd;
      pf.events = POLLOUT;
      if (::poll(&pf, 1, 30000) <= 0) return -1;  // wedged peer
      continue;
    }
    if (n < 0 && errno == EINTR) continue;
    return -1;
  }
  p->frames_out.fetch_add(1, std::memory_order_relaxed);
  p->bytes_out.fetch_add(total, std::memory_order_relaxed);
  return 0;
}

int rpcx_close_conn(void* vp, long cid) {
  auto* p = static_cast<Pump*>(vp);
  std::lock_guard<std::mutex> lk(p->mu);
  auto it = p->conns.find(cid);
  if (it == p->conns.end()) return -1;
  close_conn_locked(p, it->second);
  return 0;
}

// Post a synthetic wake event: bounces whichever thread is inside
// rpcx_next_batch out of its epoll promptly (the Python side uses this
// to hand the reactor from the background delivery thread to a getter
// thread that wants to reap its own reply inline).
void rpcx_wake(void* vp) {
  auto* p = static_cast<Pump*>(vp);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    Event e;
    e.cid = 0;
    e.kind = kKindWake;
    p->q.push_back(e);
  }
  uint64_t one = 1;
  ssize_t rc = ::write(p->wake_fd, &one, 8);
  (void)rc;
}

void rpcx_shutdown(void* vp) {
  auto* p = static_cast<Pump*>(vp);
  p->shutdown.store(true);
  uint64_t one = 1;
  ssize_t rc = ::write(p->wake_fd, &one, 8);
  (void)rc;
}

// full teardown; only call after the lane thread left rpcx_next_batch
void rpcx_destroy(void* vp) {
  auto* p = static_cast<Pump*>(vp);
  p->shutdown.store(true);
  std::lock_guard<std::mutex> rk(p->reactor_mu);
  std::lock_guard<std::mutex> lk(p->mu);
  for (auto& kv : p->conns) {
    if (!kv.second->closed) ::close(kv.second->fd);
    delete kv.second;
  }
  p->conns.clear();
  for (auto& e : p->q) std::free(e.data);
  p->q.clear();
  if (p->listen_fd >= 0) ::close(p->listen_fd);
  if (p->listen_fd_tcp >= 0) ::close(p->listen_fd_tcp);
  ::close(p->wake_fd);
  ::close(p->ep);
  delete p;
}

// out[6]: frames_in, frames_out, bytes_in, bytes_out, read_calls,
// write_calls — read_calls < frames_in is the coalescing proof
void rpcx_stats(void* vp, uint64_t* out) {
  auto* p = static_cast<Pump*>(vp);
  out[0] = p->frames_in.load();
  out[1] = p->frames_out.load();
  out[2] = p->bytes_in.load();
  out[3] = p->bytes_out.load();
  out[4] = p->read_calls.load();
  out[5] = p->write_calls.load();
}

}  // extern "C"
