// Concurrency stress harness for the plasmax store (SURVEY §5.2).
//
// Built with -fsanitize=thread by tests/test_sanitizers.py (the
// reference runs its plasma/object_manager tests under TSAN the same
// way); 8 threads hammer create/seal/get/pin/release/delete on one
// segment — any data race in the mutex discipline is a TSAN report,
// which halt_on_error turns into a nonzero exit.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <pthread.h>

extern "C" {
uint64_t px_segment_size(uint64_t heap_bytes, uint32_t nslots);
int px_init(void* base, uint64_t seg_size, uint32_t nslots);
int px_create(void* base, const uint8_t* id, uint64_t size,
              uint64_t* offset);
int px_get(void* base, const uint8_t* id, uint64_t* offset,
           uint64_t* size);
int px_seal(void* base, const uint8_t* id);
int px_release(void* base, const uint8_t* id);
int px_delete(void* base, const uint8_t* id);
int px_pin(void* base, const uint8_t* id);
}

static void* g_base;

static void make_id(uint8_t* out, int tid, int i) {
  // 24-byte object ids, unique per (thread, iteration)
  std::memset(out, 0, 24);
  std::snprintf(reinterpret_cast<char*>(out), 24, "%011d-%011d", tid, i);
}

static void* worker(void* arg) {
  const int tid = static_cast<int>(reinterpret_cast<intptr_t>(arg));
  uint64_t off, size;
  uint8_t oid[24], other[24];
  for (int i = 0; i < 500; i++) {
    make_id(oid, tid, i);
    if (px_create(g_base, oid, 4096, &off) == 0) {
      std::memset(static_cast<char*>(g_base) + off, tid, 4096);
      px_seal(g_base, oid);
      // drop the creator ref (the python client does this inside
      // seal()) — otherwise refcnt stays 1 forever, px_delete always
      // refuses, and the delete/eviction/coalesce paths under test
      // never actually run
      px_release(g_base, oid);
    }
    make_id(other, (tid + 1) % 8, i > 0 ? i - 1 : 0);
    if (px_get(g_base, other, &off, &size) == 0) {
      volatile char sink = static_cast<char*>(g_base)[off];  // read it
      (void)sink;
      px_release(g_base, other);
    }
    if (px_pin(g_base, oid) == 0) px_release(g_base, oid);
    if (i % 7 == 0) px_delete(g_base, oid);
  }
  return nullptr;
}

int main() {
  const uint32_t nslots = 8192;
  const uint64_t seg = px_segment_size(16ull * 1024 * 1024, nslots);
  static char* mem = new char[seg];
  g_base = mem;
  if (px_init(g_base, seg, nslots) != 0) {
    std::fprintf(stderr, "px_init failed\n");
    return 2;
  }
  pthread_t ts[8];
  for (intptr_t t = 0; t < 8; t++)
    pthread_create(&ts[t], nullptr, worker, reinterpret_cast<void*>(t));
  for (auto& t : ts) pthread_join(t, nullptr);
  std::printf("STRESS-OK\n");
  return 0;
}
