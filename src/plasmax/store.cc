// plasmax — shared-memory object store for the TPU-native runtime.
//
// Role-equivalent to the reference's plasma store
// (reference: src/ray/object_manager/plasma/{store.cc,object_lifecycle_manager.cc,
// eviction_policy.cc,plasma_allocator.cc}) but redesigned for this runtime:
// instead of a store *server* process with a unix-socket protocol and fd
// passing, the store is a single shared-memory segment (one mmap'd file in
// /dev/shm per node) that every worker process maps directly. All metadata —
// object index, free list, LRU queue — lives inside the segment, guarded by a
// process-shared robust mutex, so create/get/seal are a few hundred ns with no
// IPC round-trip. Rationale: on a TPU host the store's job is staging host
// arrays for jax.device_put / checkpointing; eliminating the socket hop is the
// TPU-first redesign of plasma's client protocol.
//
// Layout of the segment:
//   [Header][Slot * nslots][data heap ...]
// Object index: open-addressed hash table (linear probe) keyed by 24-byte
// object IDs. Allocator: first-fit free list with coalescing, 64-byte aligned
// payloads (zero-copy numpy/jax views need alignment). Eviction: LRU over
// sealed refcount==0 objects (reference: eviction_policy.cc LRU semantics).

#include <cstdint>
#include <cstring>
#include <pthread.h>
#include <cerrno>

namespace {

constexpr uint64_t kMagic = 0x504c41534d415859ULL;  // "PLASMAXY"
constexpr uint64_t kAlign = 64;
constexpr int kIdSize = 24;

enum SlotState : uint8_t {
  kEmpty = 0,
  kCreated = 1,   // allocated, not yet sealed (writer filling it)
  kSealed = 2,    // immutable, readable
  kTombstone = 3, // deleted; probe chains continue through it
};

struct Slot {
  uint8_t id[kIdSize];
  uint8_t state;
  int32_t refcnt;
  uint64_t offset;  // payload offset from segment base
  uint64_t size;
  // LRU doubly-linked list of evictable (sealed, refcnt==0) objects.
  // Values are slot_index + 1; 0 means "not linked".
  uint64_t lru_prev;
  uint64_t lru_next;
};

struct FreeBlock {
  uint64_t size;  // includes this header
  uint64_t next;  // offset of next free block from base; 0 = end
};

struct Header {
  uint64_t magic;
  pthread_mutex_t mutex;
  uint64_t total_size;   // whole segment
  uint64_t data_off;     // start of heap
  uint64_t data_size;    // heap bytes
  uint32_t nslots;       // power of two
  uint32_t nlive;        // created+sealed slots
  uint64_t used_bytes;   // allocated heap bytes (incl. block headers)
  uint64_t free_head;    // offset of first free block; 0 = none
  uint64_t lru_head;     // slot_index+1 of least-recently-used evictable
  uint64_t lru_tail;     // most-recently-used end
  // stats
  uint64_t num_created;
  uint64_t num_evicted;
  uint64_t bytes_evicted;
};

inline Slot* slots(void* base) {
  return reinterpret_cast<Slot*>(static_cast<char*>(base) + sizeof(Header));
}

inline uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 24-byte id.
  uint64_t h = 14695981039346656037ULL;
  for (int i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

class Locker {
 public:
  explicit Locker(Header* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->mutex);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock; state is still consistent enough for
      // metadata (we never leave multi-step invariants broken across ops).
      pthread_mutex_consistent(&h_->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&h_->mutex); }

 private:
  Header* h_;
};

// ---- slot index -------------------------------------------------------------

Slot* find_slot(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  Slot* tab = slots(base);
  uint64_t mask = h->nslots - 1;
  uint64_t i = hash_id(id) & mask;
  for (uint32_t probe = 0; probe < h->nslots; probe++, i = (i + 1) & mask) {
    Slot& s = tab[i];
    if (s.state == kEmpty) return nullptr;
    if (s.state != kTombstone && memcmp(s.id, id, kIdSize) == 0) return &s;
  }
  return nullptr;
}

Slot* alloc_slot(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  Slot* tab = slots(base);
  uint64_t mask = h->nslots - 1;
  uint64_t i = hash_id(id) & mask;
  Slot* first_free = nullptr;
  for (uint32_t probe = 0; probe < h->nslots; probe++, i = (i + 1) & mask) {
    Slot& s = tab[i];
    if (s.state == kEmpty) {
      return first_free ? first_free : &s;
    }
    if (s.state == kTombstone) {
      if (!first_free) first_free = &s;
    } else if (memcmp(s.id, id, kIdSize) == 0) {
      return nullptr;  // already exists
    }
  }
  return first_free;  // table full unless a tombstone was found
}

// ---- LRU list ---------------------------------------------------------------

inline uint64_t slot_index(void* base, Slot* s) {
  return static_cast<uint64_t>(s - slots(base));
}

void lru_unlink(void* base, Slot* s) {
  Header* h = static_cast<Header*>(base);
  Slot* tab = slots(base);
  if (s->lru_prev) tab[s->lru_prev - 1].lru_next = s->lru_next;
  else if (h->lru_head == slot_index(base, s) + 1) h->lru_head = s->lru_next;
  if (s->lru_next) tab[s->lru_next - 1].lru_prev = s->lru_prev;
  else if (h->lru_tail == slot_index(base, s) + 1) h->lru_tail = s->lru_prev;
  s->lru_prev = s->lru_next = 0;
}

void lru_push_tail(void* base, Slot* s) {
  Header* h = static_cast<Header*>(base);
  Slot* tab = slots(base);
  uint64_t me = slot_index(base, s) + 1;
  s->lru_prev = h->lru_tail;
  s->lru_next = 0;
  if (h->lru_tail) tab[h->lru_tail - 1].lru_next = me;
  h->lru_tail = me;
  if (!h->lru_head) h->lru_head = me;
}

// ---- allocator --------------------------------------------------------------

inline uint64_t round_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

inline FreeBlock* block_at(void* base, uint64_t off) {
  return reinterpret_cast<FreeBlock*>(static_cast<char*>(base) + off);
}

// Allocates `payload` bytes; returns payload offset or 0 on failure.
// Block layout: [uint64 block_size][pad to kAlign][payload]. block_size is
// stashed kAlign bytes before the payload so free() can find it.
uint64_t heap_alloc(void* base, uint64_t payload) {
  Header* h = static_cast<Header*>(base);
  uint64_t need = round_up(payload + kAlign, kAlign);
  uint64_t prev_off = 0;
  uint64_t off = h->free_head;
  while (off) {
    FreeBlock* b = block_at(base, off);
    if (b->size >= need) {
      uint64_t remain = b->size - need;
      uint64_t next;
      if (remain >= 2 * kAlign) {
        // split: keep the tail as a free block
        uint64_t tail_off = off + need;
        FreeBlock* tail = block_at(base, tail_off);
        tail->size = remain;
        tail->next = b->next;
        next = tail_off;
      } else {
        need = b->size;  // absorb the sliver
        next = b->next;
      }
      if (prev_off) block_at(base, prev_off)->next = next;
      else h->free_head = next;
      *reinterpret_cast<uint64_t*>(static_cast<char*>(base) + off) = need;
      h->used_bytes += need;
      return off + kAlign;
    }
    prev_off = off;
    off = b->next;
  }
  return 0;
}

void heap_free(void* base, uint64_t payload_off) {
  Header* h = static_cast<Header*>(base);
  uint64_t off = payload_off - kAlign;
  uint64_t size = *reinterpret_cast<uint64_t*>(static_cast<char*>(base) + off);
  h->used_bytes -= size;
  // insert into address-ordered free list, coalescing neighbors
  uint64_t prev = 0, cur = h->free_head;
  while (cur && cur < off) {
    prev = cur;
    cur = block_at(base, cur)->next;
  }
  uint64_t self = off;
  uint64_t self_size = size;
  // coalesce with next
  if (cur && self + self_size == cur) {
    self_size += block_at(base, cur)->size;
    cur = block_at(base, cur)->next;
  }
  // coalesce with prev
  if (prev && prev + block_at(base, prev)->size == self) {
    block_at(base, prev)->size += self_size;
    block_at(base, prev)->next = cur;
    return;
  }
  FreeBlock* b = block_at(base, self);
  b->size = self_size;
  b->next = cur;
  if (prev) block_at(base, prev)->next = self;
  else h->free_head = self;
}

void remove_object(void* base, Slot* s) {
  Header* h = static_cast<Header*>(base);
  lru_unlink(base, s);
  heap_free(base, s->offset);
  s->state = kTombstone;
  h->nlive--;
}

// Evict LRU sealed refcnt==0 objects until at least `need` payload bytes could
// plausibly be allocated. Returns number evicted.
int evict_for(void* base, uint64_t need) {
  Header* h = static_cast<Header*>(base);
  Slot* tab = slots(base);
  int n = 0;
  while (h->lru_head) {
    // heuristic: stop once free space exceeds need + headers
    if (h->data_size - h->used_bytes >= round_up(need + kAlign, kAlign) * 2) break;
    Slot* victim = &tab[h->lru_head - 1];
    h->num_evicted++;
    h->bytes_evicted += victim->size;
    remove_object(base, victim);
    n++;
  }
  return n;
}

}  // namespace

extern "C" {

// TEST HOOK: acquire the segment mutex and return WITHOUT releasing it.
// Lets a test process die while holding the lock, so the robust-mutex
// EOWNERDEAD recovery path (Locker above) can be exercised
// deterministically from the crash-recovery test suite.
int px_debug_lock(void* base) {
  Header* h = static_cast<Header*>(base);
  int rc = pthread_mutex_lock(&h->mutex);
  if (rc == EOWNERDEAD) {
    pthread_mutex_consistent(&h->mutex);
    return 1;
  }
  return rc == 0 ? 0 : -1;
}

// Returns required segment size for a given heap capacity + slot count.
uint64_t px_segment_size(uint64_t heap_bytes, uint32_t nslots) {
  return round_up(sizeof(Header) + sizeof(Slot) * nslots, kAlign) +
         round_up(heap_bytes, kAlign);
}

int px_init(void* base, uint64_t total_size, uint32_t nslots) {
  if (nslots == 0 || (nslots & (nslots - 1)) != 0) return -1;  // must be pow2
  Header* h = static_cast<Header*>(base);
  memset(h, 0, sizeof(Header));
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  pthread_mutexattr_destroy(&attr);
  h->total_size = total_size;
  h->nslots = nslots;
  memset(slots(base), 0, sizeof(Slot) * nslots);
  h->data_off = round_up(sizeof(Header) + sizeof(Slot) * nslots, kAlign);
  h->data_size = total_size - h->data_off;
  FreeBlock* first = block_at(base, h->data_off);
  first->size = h->data_size;
  first->next = 0;
  h->free_head = h->data_off;
  h->magic = kMagic;  // last: marks segment valid
  return 0;
}

int px_attach_check(void* base) {
  return static_cast<Header*>(base)->magic == kMagic ? 0 : -1;
}

// Create an object. Returns 0 ok (payload offset in *out_off), -1 exists,
// -2 out of memory (after eviction), -3 index full.
int px_create(void* base, const uint8_t* id, uint64_t size, uint64_t* out_off) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  if (find_slot(base, id)) return -1;
  if (h->nlive >= h->nslots - (h->nslots >> 2)) return -3;  // keep load < 75%
  uint64_t off = heap_alloc(base, size);
  if (!off) {
    evict_for(base, size);
    off = heap_alloc(base, size);
    if (!off) return -2;
  }
  Slot* s = alloc_slot(base, id);
  if (!s) {
    heap_free(base, off);
    return -3;
  }
  memcpy(s->id, id, kIdSize);
  s->state = kCreated;
  s->refcnt = 1;  // creator holds a ref until seal+release
  s->offset = off;
  s->size = size;
  s->lru_prev = s->lru_next = 0;
  h->nlive++;
  h->num_created++;
  *out_off = off;
  return 0;
}

int px_seal(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  Slot* s = find_slot(base, id);
  if (!s || s->state != kCreated) return -1;
  s->state = kSealed;
  return 0;
}

// Abort an unsealed create (writer failed): frees the allocation.
int px_abort(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  Slot* s = find_slot(base, id);
  if (!s || s->state != kCreated) return -1;
  remove_object(base, s);
  return 0;
}

// Get a sealed object: increments refcount, pins it (unlinks from LRU).
// Returns 0 ok, -1 not found, -2 not sealed yet.
int px_get(void* base, const uint8_t* id, uint64_t* out_off, uint64_t* out_size) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  Slot* s = find_slot(base, id);
  if (!s) return -1;
  if (s->state != kSealed) return -2;
  if (s->refcnt == 0) lru_unlink(base, s);
  s->refcnt++;
  *out_off = s->offset;
  *out_size = s->size;
  return 0;
}

// Release a reference (creator calls once after seal; getters once per get).
// When refcount hits 0 the object becomes evictable (joins LRU tail).
int px_release(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  Slot* s = find_slot(base, id);
  if (!s || s->refcnt <= 0) return -1;
  s->refcnt--;
  if (s->refcnt == 0 && s->state == kSealed) lru_push_tail(base, s);
  return 0;
}

// Recycle a sealed buffer for in-place rewrite (compiled-DAG channel rings:
// the writer creates the slot once, keeps its creator pin, and cycles
// seal→unseal→refill→seal per invocation — zero allocator churn, so segment
// usage stays flat across repeated graph executions). Requires exactly the
// creator's pin outstanding (refcnt==1): a reader mid-get returns -2 and the
// writer retries. -1 not found / not sealed.
int px_unseal(void* base, const uint8_t* id, uint64_t* out_off) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  Slot* s = find_slot(base, id);
  if (!s || s->state != kSealed) return -1;
  if (s->refcnt != 1) return -2;
  s->state = kCreated;
  *out_off = s->offset;
  return 0;
}

// Delete a sealed object with no outstanding refs. -1 not found, -2 in use.
int px_delete(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  Slot* s = find_slot(base, id);
  if (!s) return -1;
  if (s->refcnt > 0) return -2;
  remove_object(base, s);
  return 0;
}

int px_contains(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  Slot* s = find_slot(base, id);
  return (s && s->state == kSealed) ? 1 : 0;
}

// Debug/introspection: current reference count, or -1 if absent.
int px_refcount(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  Slot* s = find_slot(base, id);
  return s ? static_cast<int>(s->refcnt) : -1;
}

// Pin/unpin: primary copies are pinned by the owning raylet so LRU eviction
// never drops the last copy (reference: pinned objects in local_object_manager).
int px_pin(void* base, const uint8_t* id) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  Slot* s = find_slot(base, id);
  if (!s || s->state != kSealed) return -1;
  if (s->refcnt == 0) lru_unlink(base, s);
  s->refcnt++;
  return 0;
}

uint64_t px_used_bytes(void* base) { return static_cast<Header*>(base)->used_bytes; }
uint64_t px_capacity(void* base) { return static_cast<Header*>(base)->data_size; }
uint64_t px_num_objects(void* base) { return static_cast<Header*>(base)->nlive; }
uint64_t px_num_evicted(void* base) { return static_cast<Header*>(base)->num_evicted; }

// Batched stats readout for metrics export.
void px_stats(void* base, uint64_t* out6) {
  Header* h = static_cast<Header*>(base);
  Locker lk(h);
  out6[0] = h->used_bytes;
  out6[1] = h->data_size;
  out6[2] = h->nlive;
  out6[3] = h->num_created;
  out6[4] = h->num_evicted;
  out6[5] = h->bytes_evicted;
}

}  // extern "C"
