// schedcore — the raylet's dispatch hot loop in native code.
//
// Reference analogue: src/ray/raylet/scheduling/ — ClusterResourceData's
// fixed-point resource vectors (fixed_point.h), LocalTaskManager's
// per-SchedulingClass pending queues and
// DispatchScheduledTasksToWorkers (local_task_manager.cc:99), and
// placement_group_resource_manager.cc's conversion of committed bundles
// into node-local resource instances.  This is a re-design, not a port:
// one flat ledger owns the node pool, the per-bundle pools, and the
// concrete TPU chip sets, and a single poll() walks scheduling-class
// HEADS, atomically acquiring resources for every dispatchable task —
// the caller (the Python raylet) receives a batch of (task, chips)
// decisions and handles policy (spillback, worker pools, RPCs) above.
//
// Resources are fixed-point int64 at 1/10000 granularity (reference:
// fixed_point.h uses the same idea) so feasibility needs no float
// epsilon.  Built like src/plasmax: plain C ABI, loaded via ctypes,
// compiled on first use with g++.
//
// Semantics mirrored from the Python ledger (raylet.py):
//   - acquire is all-or-nothing: full demand + concrete chip IDs.
//   - a bundle-bound task is only feasible while its pool exists.
//   - releasing into a returned (gone) pool credits the NODE with the
//     chips (and the TPU count follows the chips) but NOT the other
//     resources — those were credited when the bundle was returned.
//   - returning a bundle credits non-TPU resources in full, but only
//     the chips physically in the pool rejoin the node; chips held by
//     a still-running task of the PG come back on its release.

#include <cstdint>
#include <cstring>
#include <cmath>
#include <algorithm>
#include <deque>
#include <unordered_map>
#include <vector>

namespace {

typedef int64_t fp_t;  // fixed-point resource amount
static inline fp_t to_fp(double v) { return (fp_t)llround(v * 10000.0); }
// demands round to NEAREST with a nonzero floor: plain rounding keeps
// parity with the float ledger for non-representable fractions (three
// 1/3-CPU tasks fit on 1.0 CPU: 3*3333 <= 10000), while the floor
// keeps a sub-granularity demand (4e-5 of a resource the node lacks)
// from rounding to "free" and passing feasibility the float path fails
static inline fp_t to_fp_demand(double v) {
  fp_t fp = (fp_t)llround(v * 10000.0);
  if (fp == 0 && v > 0.0) fp = 1;
  return fp;
}
static inline double from_fp(fp_t v) { return (double)v / 10000.0; }

struct Pool {
  std::vector<fp_t> avail;          // indexed by resource id, lazily grown
  std::vector<int32_t> chips;       // sorted ascending
  std::vector<std::pair<int, fp_t>> committed;  // original bundle amounts
};

struct Prepared {                   // bundle between prepare and commit
  std::vector<std::pair<int, fp_t>> res;
  std::vector<int32_t> chips;
};

struct Class {
  std::vector<std::pair<int, fp_t>> demand;  // (res_id, amount)
  int tpu = 0;                // concrete chips needed
  long long bundle = -1;      // -1 = node pool
  std::deque<uint64_t> q;     // queued task tags, FIFO
  bool active = false;        // member of Core::active
};

struct Core {
  std::vector<fp_t> node_avail;
  std::vector<int32_t> node_chips;                 // sorted
  std::unordered_map<long long, Pool> pools;       // committed bundles
  std::unordered_map<long long, Prepared> prepared;
  std::vector<Class> classes;
  std::vector<int> active;                         // classes with queued work
  long long npending = 0;
  int tpu_res = -1;                                // res id of "TPU"
  size_t blocked_rot = 0;   // rotates blocked-head reporting (see scx_poll)
};

static inline fp_t vec_get(const std::vector<fp_t>& v, int id) {
  return (size_t)id < v.size() ? v[(size_t)id] : 0;
}
static inline void vec_add(std::vector<fp_t>& v, int id, fp_t amt) {
  if ((size_t)id >= v.size()) v.resize((size_t)id + 1, 0);
  v[(size_t)id] += amt;
}

static inline void chips_insert(std::vector<int32_t>& dst,
                                const int32_t* chips, int n) {
  if (n <= 0) return;
  dst.insert(dst.end(), chips, chips + n);
  std::sort(dst.begin(), dst.end());
}

// all-or-nothing feasibility of cls against its pool; does not mutate
static bool feasible(Core* c, const Class& k) {
  const std::vector<fp_t>* avail;
  const std::vector<int32_t>* chips;
  if (k.bundle >= 0) {
    auto it = c->pools.find(k.bundle);
    if (it == c->pools.end()) return false;   // pool gone / not committed
    avail = &it->second.avail;
    chips = &it->second.chips;
  } else {
    avail = &c->node_avail;
    chips = &c->node_chips;
  }
  if ((int)chips->size() < k.tpu) return false;
  for (const auto& d : k.demand)
    if (vec_get(*avail, d.first) < d.second) return false;
  return true;
}

// atomic take; fills chips_out; returns chip count or -1 when not
// feasible.  Callers bound the write: scx_poll via maxchips, scx_acquire
// via its maxout parameter.
static int acquire(Core* c, Class& k, int32_t* chips_out) {
  std::vector<fp_t>* avail;
  std::vector<int32_t>* chips;
  if (k.bundle >= 0) {
    auto it = c->pools.find(k.bundle);
    if (it == c->pools.end()) return -1;
    avail = &it->second.avail;
    chips = &it->second.chips;
  } else {
    avail = &c->node_avail;
    chips = &c->node_chips;
  }
  if ((int)chips->size() < k.tpu) return -1;
  for (const auto& d : k.demand)
    if (vec_get(*avail, d.first) < d.second) return -1;
  for (const auto& d : k.demand) vec_add(*avail, d.first, -d.second);
  for (int i = 0; i < k.tpu; i++) chips_out[i] = (*chips)[(size_t)i];
  chips->erase(chips->begin(), chips->begin() + k.tpu);
  return k.tpu;
}

static void activate(Core* c, int cls) {
  Class& k = c->classes[(size_t)cls];
  if (!k.active) { k.active = true; c->active.push_back(cls); }
}

}  // namespace

extern "C" {

void* scx_create() { return new Core(); }
void scx_destroy(void* h) { delete (Core*)h; }

void scx_set_tpu_res(void* h, int res) { ((Core*)h)->tpu_res = res; }

void scx_node_add(void* h, int res, double v) {
  vec_add(((Core*)h)->node_avail, res, to_fp(v));
}

double scx_node_get(void* h, int res) {
  return from_fp(vec_get(((Core*)h)->node_avail, res));
}

int scx_node_nres(void* h) { return (int)((Core*)h)->node_avail.size(); }

void scx_node_chips_add(void* h, const int32_t* chips, int n) {
  chips_insert(((Core*)h)->node_chips, chips, n);
}

int scx_node_chips(void* h, int32_t* out, int maxn) {
  Core* c = (Core*)h;
  int n = (int)std::min((size_t)maxn, c->node_chips.size());
  if (n > 0) memcpy(out, c->node_chips.data(), sizeof(int32_t) * (size_t)n);
  return (int)c->node_chips.size();
}

int scx_class(void* h, const int32_t* res, const double* amt, int n,
              int tpu, long long bundle) {
  Core* c = (Core*)h;
  Class k;
  k.demand.reserve((size_t)n);
  for (int i = 0; i < n; i++)
    k.demand.emplace_back(res[i], to_fp_demand(amt[i]));
  k.tpu = tpu;
  k.bundle = bundle;
  c->classes.push_back(std::move(k));
  return (int)c->classes.size() - 1;
}

// Tombstone empty classes so a long-lived raylet seeing many distinct
// demand vectors does not grow Core::classes without bound (the Python
// side drops its interning entries for the returned ids and a later
// identical demand re-interns a fresh class — accounting-neutral,
// because release() re-interns by demand, and bundle classes re-bind
// their pool through the still-interned bundle id).
int scx_gc(void* h, int32_t* freed, int maxn) {
  Core* c = (Core*)h;
  int n = 0;
  for (size_t ci = 0; ci < c->classes.size() && n < maxn; ci++) {
    Class& k = c->classes[ci];
    if (k.bundle == -2 || !k.q.empty() || k.active) continue;
    if (k.demand.empty() && k.tpu == 0 && k.bundle == -1)
      continue;  // already a tombstone-shaped empty class
    freed[n++] = (int32_t)ci;
    k.demand.clear();
    k.demand.shrink_to_fit();
    k.bundle = -2;
  }
  return n;
}

// ----------------------------------------------------------------- queues

void scx_push(void* h, int cls, uint64_t tag) {
  Core* c = (Core*)h;
  c->classes[(size_t)cls].q.push_back(tag);
  c->npending++;
  activate(c, cls);
}

void scx_push_front(void* h, int cls, uint64_t tag) {
  Core* c = (Core*)h;
  c->classes[(size_t)cls].q.push_front(tag);
  c->npending++;
  activate(c, cls);
}

int scx_remove(void* h, int cls, uint64_t tag) {
  Core* c = (Core*)h;
  auto& q = c->classes[(size_t)cls].q;
  for (auto it = q.begin(); it != q.end(); ++it)
    if (*it == tag) { q.erase(it); c->npending--; return 1; }
  return 0;
}

uint64_t scx_head(void* h, int cls) {
  auto& q = ((Core*)h)->classes[(size_t)cls].q;
  return q.empty() ? 0 : q.front();
}

uint64_t scx_pop_head(void* h, int cls) {
  Core* c = (Core*)h;
  auto& q = c->classes[(size_t)cls].q;
  if (q.empty()) return 0;
  uint64_t t = q.front();
  q.pop_front();
  c->npending--;
  return t;
}

long long scx_pending(void* h) { return ((Core*)h)->npending; }

// ------------------------------------------------------------- resources

int scx_feasible(void* h, int cls) {
  Core* c = (Core*)h;
  return feasible(c, c->classes[(size_t)cls]) ? 1 : 0;
}

int scx_acquire(void* h, int cls, int32_t* chips_out, int maxout) {
  Core* c = (Core*)h;
  Class& k = c->classes[(size_t)cls];
  if (k.tpu > maxout) return -1;  // caller's buffer cannot hold the chips
  return acquire(c, k, chips_out);
}

void scx_release(void* h, int cls, const int32_t* chips, int n) {
  Core* c = (Core*)h;
  Class& k = c->classes[(size_t)cls];
  if (k.bundle >= 0) {
    auto it = c->pools.find(k.bundle);
    if (it != c->pools.end()) {
      for (const auto& d : k.demand) vec_add(it->second.avail, d.first, d.second);
      chips_insert(it->second.chips, chips, n);
    } else {
      // bundle returned while the task ran: chips rejoin the NODE and
      // the node's TPU count follows them; nothing else is credited
      chips_insert(c->node_chips, chips, n);
      if (c->tpu_res >= 0)
        vec_add(c->node_avail, c->tpu_res, to_fp((double)n));
    }
    return;
  }
  for (const auto& d : k.demand) vec_add(c->node_avail, d.first, d.second);
  chips_insert(c->node_chips, chips, n);
}

// --------------------------------------------------------------- bundles

int scx_prepare(void* h, long long bundle, const int32_t* res,
                const double* amt, int n, int n_tpu) {
  Core* c = (Core*)h;
  if (c->prepared.count(bundle) || c->pools.count(bundle)) return 1;  // idempotent
  for (int i = 0; i < n; i++)
    if (vec_get(c->node_avail, res[i]) < to_fp_demand(amt[i])) return 0;
  if ((int)c->node_chips.size() < n_tpu) return 0;
  Prepared p;
  for (int i = 0; i < n; i++) {
    vec_add(c->node_avail, res[i], -to_fp_demand(amt[i]));
    p.res.emplace_back(res[i], to_fp_demand(amt[i]));
  }
  p.chips.assign(c->node_chips.begin(), c->node_chips.begin() + n_tpu);
  c->node_chips.erase(c->node_chips.begin(), c->node_chips.begin() + n_tpu);
  c->prepared.emplace(bundle, std::move(p));
  return 1;
}

int scx_commit(void* h, long long bundle) {
  Core* c = (Core*)h;
  if (c->pools.count(bundle)) return 1;  // idempotent retry
  auto it = c->prepared.find(bundle);
  if (it == c->prepared.end()) return 0;
  Pool pool;
  for (const auto& d : it->second.res) vec_add(pool.avail, d.first, d.second);
  pool.chips = std::move(it->second.chips);
  pool.committed = std::move(it->second.res);
  c->prepared.erase(it);
  c->pools.emplace(bundle, std::move(pool));
  return 1;
}

int scx_cancel_bundle(void* h, long long bundle) {
  Core* c = (Core*)h;
  auto it = c->prepared.find(bundle);
  if (it == c->prepared.end()) return 0;
  for (const auto& d : it->second.res) vec_add(c->node_avail, d.first, d.second);
  chips_insert(c->node_chips, it->second.chips.data(),
               (int)it->second.chips.size());
  c->prepared.erase(it);
  return 1;
}

int scx_return_bundle(void* h, long long bundle) {
  Core* c = (Core*)h;
  auto it = c->pools.find(bundle);
  if (it == c->pools.end()) return 0;
  // Credit the ORIGINAL committed amounts for non-TPU resources (tasks
  // of this PG still running will find the pool gone on release and
  // credit nothing but their chips); only chips physically in the pool
  // rejoin the node now, and the node's TPU count follows the chips.
  for (const auto& d : it->second.committed)
    if (d.first != c->tpu_res) vec_add(c->node_avail, d.first, d.second);
  int nret = (int)it->second.chips.size();
  chips_insert(c->node_chips, it->second.chips.data(), nret);
  if (c->tpu_res >= 0)
    vec_add(c->node_avail, c->tpu_res, to_fp((double)nret));
  c->pools.erase(it);
  return 1;
}

int scx_has_bundle(void* h, long long bundle) {
  Core* c = (Core*)h;
  return (c->prepared.count(bundle) || c->pools.count(bundle)) ? 1 : 0;
}

int scx_bundle_committed(void* h, long long bundle) {
  return ((Core*)h)->pools.count(bundle) ? 1 : 0;
}

// -------------------------------------------------------------- hot loop

// Walk the heads of every active scheduling class; atomically acquire
// resources for each dispatchable head and emit it.  Infeasible heads
// are reported in blocked_* so the caller can run spillback policy.
// When there are more blocked heads than maxblocked, reporting ROTATES
// across polls (blocked_rot) so every stuck class is eventually seen
// by the spillback policy — overflow must not hide a class forever,
// and signalling `more` for it would spin the dispatch loop.
// Returns the number of dispatches; *more is set if the output buffers
// filled while dispatchable work remained (caller should poll again).
int scx_poll(void* h, uint64_t* tags, int32_t* clss, int32_t* chip_off,
             int32_t* chip_cnt, int32_t* chips, int maxn, int maxchips,
             uint64_t* blocked_tags, int32_t* blocked_cls, int* nblocked,
             int maxblocked, int* more) {
  Core* c = (Core*)h;
  int n = 0, nchips = 0, nb = 0;
  long long blocked_total = 0;
  *more = 0;
  size_t w = 0;
  size_t nact = c->active.size();
  size_t rot = nact ? (c->blocked_rot % nact) : 0;
  c->blocked_rot += (size_t)maxblocked;  // window-sized stride
  for (size_t j = 0; j < nact; j++) {
    // dispatch scan stays in stable order; only the blocked-report
    // window rotates, via a rotated *report* index below
    size_t i = j;
    int ci = c->active[i];
    Class& k = c->classes[(size_t)ci];
    if (k.q.empty()) { k.active = false; continue; }  // compact out
    c->active[w++] = ci;
    // Rotating report window: blocked classes (either oversized or
    // currently-infeasible heads) share the maxblocked report slots
    // across polls so none can starve the others.
    const bool in_window =
        (j >= rot && (long long)(j - rot) < (long long)maxblocked) ||
        (j < rot && (long long)(nact - rot + j) < (long long)maxblocked);
    while (!k.q.empty()) {
      if (k.tpu > maxchips) {
        // can NEVER fit the chip buffer: report blocked (the caller's
        // spillback policy handles it) — `more` would busy-spin
        blocked_total++;
        if (in_window && nb < maxblocked) {
          blocked_tags[nb] = k.q.front();
          blocked_cls[nb] = ci;
          nb++;
        }
        break;
      }
      if (n >= maxn || nchips + k.tpu > maxchips) { *more = 1; break; }
      int got = acquire(c, k, chips + nchips);
      if (got < 0) {
        // blocked head: report for spillback policy, rotated window
        blocked_total++;
        if (in_window && nb < maxblocked) {
          blocked_tags[nb] = k.q.front();
          blocked_cls[nb] = ci;
          nb++;
        }
        break;
      }
      tags[n] = k.q.front();
      clss[n] = ci;
      chip_off[n] = nchips;
      chip_cnt[n] = got;
      nchips += got;
      n++;
      k.q.pop_front();
      c->npending--;
    }
    if (k.q.empty()) { k.active = false; w--; }
  }
  c->active.resize(w);
  *nblocked = nb;
  return n;
}

// Drain every queued task of classes bound to `bundle` (the PG was
// returned; they can never run) and FREE those classes — a long-
// running raylet churning placement groups must not accumulate dead
// Class structs.  Returns count written to tags.
int scx_drain_bundle(void* h, long long bundle, uint64_t* tags, int maxn) {
  Core* c = (Core*)h;
  int n = 0;
  for (size_t ci = 0; ci < c->classes.size(); ci++) {
    Class& k = c->classes[ci];
    if (k.bundle != bundle) continue;
    while (!k.q.empty() && n < maxn) {
      tags[n++] = k.q.front();
      k.q.pop_front();
      c->npending--;
    }
    if (k.q.empty()) {
      // tombstone: shrink to nothing; the id is never reused (the
      // Python side drops its interning entry in the same call)
      k.demand.clear();
      k.demand.shrink_to_fit();
      k.bundle = -2;  // never matches a live bundle again
    }
  }
  return n;
}

}  // extern "C"
