"""RLlib next-gen stack: RLModule + Learner + LearnerGroup
(run: python examples/08_rlmodule_learner.py).

Reference analogue: rllib/core — the RLModule owns the network (three
jitted forwards), the Learner owns losses/optimizers, the LearnerGroup
scales to data-parallel learner actors. Rollouts below come from the
module's own forward_exploration over the vector env.
"""

import os

# RL control policies are tiny MLPs — CPU is the right backend for the
# driver-side module; TPU training rides Learner/mesh paths instead.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

import ray_tpu
from ray_tpu.rllib import LearnerGroup, PPOLearner, RLModuleSpec
from ray_tpu.rllib.env import CartPoleEnv
from ray_tpu.rllib.postprocessing import compute_advantages
from ray_tpu.rllib.sample_batch import SampleBatch


def rollout(module, env, horizon=200):
    obs, _ = env.reset()
    cols = {k: [] for k in ("obs", "actions", "action_logp", "rewards",
                            "dones", "vf_preds")}
    for _ in range(horizon):
        out = module.forward_exploration({"obs": obs[None]})
        action = int(out["actions"][0])
        next_obs, reward, terminated, truncated, _ = env.step(action)
        done = terminated or truncated
        cols["obs"].append(obs)
        cols["actions"].append(action)
        cols["action_logp"].append(float(out["action_logp"][0]))
        cols["rewards"].append(reward)
        cols["dones"].append(done)
        cols["vf_preds"].append(float(out["vf_preds"][0]))
        obs = env.reset()[0] if done else next_obs
    return {k: np.asarray(v) for k, v in cols.items()}


def main():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    spec = RLModuleSpec(observation_space=CartPoleEnv().observation_space,
                        action_space=CartPoleEnv().action_space)
    group = LearnerGroup(
        PPOLearner, num_learners=2,
        learner_kwargs={"module_spec": spec,
                        "config": {"lr": 5e-4, "clip_param": 0.2}})
    # a local module for rollouts, synced from the group each iteration
    actor_module = spec.build()
    env = CartPoleEnv()
    for it in range(5):
        actor_module.set_state(
            group.get_state()["module"]["default_policy"])
        batch = rollout(actor_module, env)
        sb = SampleBatch(batch)
        post = compute_advantages(sb, last_value=0.0, gamma=0.99,
                                  lambda_=0.95)
        train_batch = {
            "obs": post["obs"].astype(np.float32),
            "actions": post["actions"].astype(np.int32),
            "action_logp": post["action_logp"].astype(np.float32),
            "advantages": post["advantages"].astype(np.float32),
            "value_targets": post["value_targets"].astype(np.float32),
        }
        stats = group.update_from_batch(train_batch)
        mean_r = float(np.sum(batch["rewards"]) /
                       max(1, int(np.sum(batch["dones"]))))
        print(f"iter {it}: reward/episode ~{mean_r:.1f}  {stats}")
    group.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
