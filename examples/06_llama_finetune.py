"""Fine-tune a Llama-family decoder (single-host walkthrough).

Builds a (tiny) Llama with grouped-query attention and runs a jitted
train loop end-to-end — the minimal template for the model family.
For the sharded multi-chip path, wrap the same model/loss in the SPMD
trainer exactly as `examples/02_train_spmd.py` does for ResNet (mesh
axes dp/fsdp/tp/sp via `parallel.mesh.MeshSpec`); weights import from
a HF checkpoint via `import_hf_llama` when one is on disk.

Run: python examples/06_llama_finetune.py
"""

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models.llama import (LlamaConfig, LlamaModel,
                                      causal_lm_loss)

    cfg = LlamaConfig.tiny(vocab_size=256)
    model = LlamaModel(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 64, (8, 64)))

    params = model.init(jax.random.PRNGKey(0), ids)
    opt = optax.adamw(3e-4)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(model.apply(p, batch), batch))(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    for i in range(10):
        params, opt_state, loss = step(params, opt_state, ids)
        if i % 3 == 0:
            print(f"step {i}: loss {float(loss):.3f}")
    print("done — GQA decoder trains end-to-end")


if __name__ == "__main__":
    main()
