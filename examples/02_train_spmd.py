"""Train: DataParallelTrainer running a jitted SPMD step on the gang
(run: python examples/02_train_spmd.py)."""
import numpy as np

import ray_tpu
from ray_tpu import data as rdata
from ray_tpu.train import (DataParallelTrainer, ScalingConfig, report,
                           get_dataset_shard)


def train_loop(config):
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(w, batch):
        x, y = batch["x"], batch["y"]
        pred = x @ w
        loss = jnp.mean((pred - y) ** 2)
        grad = 2 * x.T @ (pred - y) / len(x)
        return w - config["lr"] * grad, loss

    w = jnp.zeros((4,))
    shard = get_dataset_shard("train")
    for epoch in range(config["epochs"]):
        for batch in shard.iter_batches(batch_size=32,
                                        batch_format="numpy"):
            w, loss = step(w, batch)
        report({"epoch": epoch, "loss": float(loss)})


def main():
    ray_tpu.init(num_cpus=4)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((256, 4)).astype(np.float32)
    ds = rdata.from_numpy({"x": x, "y": (x @ [1, -2, 3, 0.5]).astype(np.float32)})
    trainer = DataParallelTrainer(
        train_loop, train_loop_config={"lr": 0.1, "epochs": 3},
        datasets={"train": ds},
        scaling_config=ScalingConfig(num_workers=2))
    result = trainer.fit()
    print("final:", result.metrics)
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
