"""Serve: a two-route deployment graph behind the HTTP proxy
(run: python examples/04_serve_graph.py, then curl the printed URLs)."""
import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.drivers import DAGDriver


@serve.deployment
class Doubler:
    def __call__(self, x=0):
        return {"doubled": 2 * x}


@serve.deployment(num_replicas=2)
class Negator:
    def __call__(self, x=0):
        return {"negated": -x}


def main():
    ray_tpu.init(num_cpus=4)
    app = DAGDriver.bind({"/double": Doubler.bind(),
                          "/negate": Negator.bind()})
    serve.run(app, http_port=8000)
    print("POST http://127.0.0.1:8000/double  {'x'-less JSON body = arg}")
    print("POST http://127.0.0.1:8000/negate")
    input("serving; press enter to stop\n")
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
