"""Tune: TPE search with ASHA early stopping
(run: python examples/03_tune_search.py)."""
import ray_tpu
from ray_tpu import tune
from ray_tpu.air import session


def objective(config):
    acc = 0.0
    for i in range(20):
        acc += config["lr"] * (1.0 - acc)  # toy convergence curve
        session.report({"accuracy": acc})


def main():
    ray_tpu.init(num_cpus=4)
    tuner = tune.Tuner(
        objective,
        param_space={"lr": tune.loguniform(1e-3, 1.0)},
        tune_config=tune.TuneConfig(
            metric="accuracy", mode="max", num_samples=12,
            search_alg=tune.TPESearcher(num_samples=12, seed=0),
            scheduler=tune.ASHAScheduler(max_t=20, grace_period=4)))
    best = tuner.fit().get_best_result()
    print("best lr:", best.config["lr"], "acc:", best.metrics["accuracy"])
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
