"""RLlib: PPO on CartPole with evaluation workers
(run: python examples/05_rllib_ppo.py)."""
import ray_tpu
from ray_tpu.rllib.algorithms.ppo import PPOConfig


def main():
    ray_tpu.init(num_cpus=4)
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_envs_per_worker=8, rollout_fragment_length=64)
            .training(train_batch_size=512, lr=3e-3)
            .evaluation(evaluation_interval=5, evaluation_num_episodes=3)
            .debugging(seed=0)
            .build())
    for i in range(20):
        r = algo.step()
        print(f"iter {i}: reward={r['episode_reward_mean']:.1f}")
        if r.get("evaluation"):
            print("  eval:", r["evaluation"]["episode_reward_mean"])
    algo.cleanup()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
