"""Core API: tasks, actors, objects (run: python examples/01_core_tasks_actors.py)."""
import numpy as np

import ray_tpu


def main():
    ray_tpu.init(num_cpus=4)

    @ray_tpu.remote
    def preprocess(x):
        return x * 2

    @ray_tpu.remote
    class Accumulator:
        def __init__(self):
            self.total = 0.0

        def add(self, arr):
            self.total += float(np.sum(arr))
            return self.total

    big = ray_tpu.put(np.ones((1024, 1024), np.float32))  # plasma, zero-copy reads
    acc = Accumulator.remote()
    doubled = preprocess.remote(big)
    print("total:", ray_tpu.get(acc.add.remote(doubled)))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
