"""Train-API breadth: SklearnTrainer (cluster-parallel CV) and
RLTrainer (an RLlib algorithm through the Train API).

Run: python examples/07_sklearn_rl_trainers.py
"""

import numpy as np


def main():
    import ray_tpu
    from ray_tpu import data
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train import RLTrainer, SklearnTrainer

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        # ---- sklearn: fit + 3-fold CV, each fold its own cluster task
        from sklearn.linear_model import LogisticRegression

        rng = np.random.default_rng(0)
        X = rng.normal(size=(200, 4))
        y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
        rows = [{"f0": float(a), "f1": float(b), "f2": float(c),
                 "f3": float(d), "label": int(t)}
                for (a, b, c, d), t in zip(X, y)]
        result = SklearnTrainer(
            estimator=LogisticRegression(max_iter=200),
            label_column="label", cv=3,
            scaling_config=ScalingConfig(num_workers=1),
            datasets={"train": data.from_items(rows)},
        ).fit()
        print(f"sklearn: train-score={result.metrics['train-score']:.3f} "
              f"cv={result.metrics['cv_score_mean']:.3f}"
              f"±{result.metrics['cv_score_std']:.3f}")
        model = SklearnTrainer.get_model(result.checkpoint)
        print("sklearn: restored model predicts",
              model.predict(np.zeros((1, 4)))[0])

        # ---- RLlib through Train: PG on CartPole, checkpoint -> policy
        result = RLTrainer(
            algorithm="PG",
            config={"env": "CartPole-v1", "num_workers": 0,
                    "train_batch_size": 200, "lr": 1e-2},
            num_iterations=2,
            scaling_config=ScalingConfig(num_workers=1),
        ).fit()
        print(f"rl: {result.metrics['training_iteration']} iterations, "
              f"reward={result.metrics['episode_reward_mean']}")
        algo = RLTrainer.restore_algorithm(result.checkpoint)
        action = algo.compute_single_action(
            np.zeros(4, dtype=np.float32))
        print("rl: restored policy acts:", action)
        algo.cleanup()
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
