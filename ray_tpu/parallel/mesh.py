"""Device mesh construction: the parallelism substrate.

This is the TPU-native replacement for the reference's process-group world
(reference: ray.train torch process groups + util/collective NCCL groups).
Instead of N processes each owning one GPU and gradient sync via NCCL, a
ray_tpu SPMD job holds a single logical `jax.sharding.Mesh` spanning every
chip of the slice (or multi-slice), with named axes:

    dp   — data parallel (batch split; psum of grads)
    fsdp — fully-sharded data parallel (weights sharded along with batch)
    tp   — tensor parallel (weight matrices split; collectives inside layers)
    pp   — pipeline parallel (layer groups; ppermute microbatches)
    sp   — sequence/context parallel (ring attention over sequence shards)
    ep   — expert parallel (MoE expert sharding + all_to_all dispatch)

`MeshSpec` validates that the axis product matches the device count, orders
axes so the fastest-varying axes land on ICI-adjacent devices (tp/sp
innermost — they carry per-layer collectives; dp outermost — it can cross
DCN), and builds the Mesh. The "How to Scale Your Model" recipe: pick a mesh,
annotate shardings, let XLA insert collectives.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dp", "fsdp", "pp", "ep", "sp", "tp")
# innermost (rightmost) axes get ICI-contiguous devices; tp/sp carry the
# highest-frequency collectives so they sit innermost.


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout, independent of physical devices."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    @property
    def num_devices(self) -> int:
        return math.prod(self.axis_sizes().values())

    def active_axes(self) -> List[str]:
        return [a for a in AXIS_ORDER if getattr(self, a) > 1]

    @classmethod
    def from_dict(cls, d: Dict[str, int]) -> "MeshSpec":
        unknown = set(d) - set(AXIS_ORDER)
        if unknown:
            raise ValueError(f"unknown mesh axes {unknown}; valid: {AXIS_ORDER}")
        return cls(**d)

    def with_auto_dp(self, num_devices: int) -> "MeshSpec":
        """Fill the dp axis to absorb remaining devices."""
        fixed = self.num_devices // max(self.dp, 1)
        if num_devices % fixed != 0:
            raise ValueError(
                f"{num_devices} devices not divisible by non-dp axes ({fixed})")
        return dataclasses.replace(self, dp=num_devices // fixed)

    def build(self, devices: Optional[Sequence] = None):
        """Build a jax.sharding.Mesh over the given (or all) devices."""
        import jax
        from jax.sharding import Mesh

        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if self.num_devices != len(devices):
            raise ValueError(
                f"MeshSpec wants {self.num_devices} devices "
                f"({self.axis_sizes()}), got {len(devices)}")
        shape = tuple(self.axis_sizes()[a] for a in AXIS_ORDER)
        arr = _topology_aware_reshape(devices, shape)
        return Mesh(arr, AXIS_ORDER)

    def describe(self) -> str:
        active = {a: getattr(self, a) for a in self.active_axes()}
        return f"MeshSpec({active or 'single-device'})"


def _topology_aware_reshape(devices: List, shape: Tuple[int, ...]) -> np.ndarray:
    """Order devices so innermost mesh axes are ICI-adjacent.

    On TPU, jax device ids are assigned so that consecutive ids are
    physically adjacent within a tray; jax.experimental.mesh_utils does the
    full topology-aware assignment for pod slices — use it when available and
    fall back to id-order otherwise (CPU meshes in tests don't care).
    """
    try:
        from jax.experimental import mesh_utils
        plat = getattr(devices[0], "platform", "")
        if plat == "tpu" and len(devices) > 1:
            return mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        pass
    ordered = sorted(devices, key=lambda d: (getattr(d, "process_index", 0),
                                             d.id))
    return np.array(ordered).reshape(shape)


def single_axis_mesh(axis: str, devices: Optional[Sequence] = None):
    """Convenience: a 1-axis mesh (e.g. pure data parallel)."""
    import jax
    if devices is None:
        devices = jax.devices()
    return MeshSpec.from_dict({axis: len(devices)}).build(devices)


# ---------------------------------------------------------------------------
# Sharding rules


def param_sharding(mesh, path: Tuple[str, ...], shape: Tuple[int, ...],
                   spec: MeshSpec):
    """Default parameter PartitionSpec under a MeshSpec.

    Policy (the standard megatron/fsdp hybrid):
      - tp axis shards the largest contraction dim of matmul weights
      - fsdp shards the largest remaining dim
      - biases/scales/small params replicate
    Models can override per-layer; this default keeps MXU-friendly layouts
    (shard model dims, never the minor-most 128-lane dim below tile size).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    ndim = len(shape)
    assign: List[Optional[str]] = [None] * ndim
    if ndim >= 2:
        order = sorted(range(ndim), key=lambda i: -shape[i])
        if spec.tp > 1:
            for i in order:
                if shape[i] % spec.tp == 0 and _tp_hint(path, i, ndim):
                    assign[i] = "tp"
                    break
        if spec.fsdp > 1:
            for i in order:
                if assign[i] is None and shape[i] % spec.fsdp == 0:
                    assign[i] = "fsdp"
                    break
    elif ndim == 1 and spec.fsdp > 1 and shape[0] % spec.fsdp == 0 and \
            shape[0] >= 1024:
        assign[0] = "fsdp"
    return NamedSharding(mesh, P(*assign))


def _tp_hint(path: Tuple[str, ...], dim: int, ndim: int) -> bool:
    """Heuristic: attention/mlp 'out' projections shard input dim, others
    shard output dim — this alternates collectives correctly for megatron
    style TP. Path entries are param-tree keys."""
    name = "/".join(str(p) for p in path).lower()
    if any(k in name for k in ("out_proj", "down_proj", "wo", "o_proj", "fc2")):
        return dim == 0
    return dim == ndim - 1


def data_sharding(mesh, batch_ndim: int = 1):
    """Shard the batch dim over (dp, fsdp); replicate the rest."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    axes: list = [("dp", "fsdp")] + [None] * (batch_ndim - 1)
    return NamedSharding(mesh, P(*axes))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def shard_params(params, mesh, spec: MeshSpec):
    """Apply param_sharding across a pytree; returns sharded params."""
    import jax
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    leaves, treedef = tree_flatten_with_path(params)
    out = []
    for path, leaf in leaves:
        keys = tuple(getattr(p, "key", getattr(p, "idx", str(p)))
                     for p in path)
        sh = param_sharding(mesh, keys, leaf.shape, spec)
        out.append(jax.device_put(leaf, sh))
    return tree_unflatten(treedef, out)


def sharding_pytree(params, mesh, spec: MeshSpec):
    """The NamedSharding pytree for params (for jit in/out shardings)."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    leaves, treedef = tree_flatten_with_path(params)
    out = []
    for path, leaf in leaves:
        keys = tuple(getattr(p, "key", getattr(p, "idx", str(p)))
                     for p in path)
        out.append(param_sharding(mesh, keys, leaf.shape, spec))
    return tree_unflatten(treedef, out)
