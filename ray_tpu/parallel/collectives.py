"""Collective communication facade.

Role-equivalent to the reference's util/collective API
(reference: python/ray/util/collective/collective.py — allreduce:258,
reduce:311, broadcast:373, allgather:423, reducescatter:472, barrier:298)
with the backend swapped: instead of NCCL-via-cupy / Gloo-via-pygloo process
groups, ops lower to XLA collectives (jax.lax.psum / all_gather /
ppermute / psum_scatter) over the ICI mesh inside jit/shard_map programs,
and the host-level group bootstrap is jax.distributed (coordination service
over DCN) with rendezvous through the GCS KV — replacing
TCPStore/pygloo-store rendezvous.

Two API layers:
1. In-program (inside jit/shard_map): thin wrappers over jax.lax.* keyed by
   mesh axis name — use these in model/step code.
2. Host-level (driver/actor code): ``init_collective_group`` +
   ``allreduce``-style eager ops that build a one-off pjit program over the
   group's mesh. Matches the reference API shape for drop-in porting.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# --------------------------------------------------------------------------
# Layer 1: in-program collectives (use inside jit / shard_map)


def psum(x, axis: str):
    import jax
    return jax.lax.psum(x, axis_name=axis)


def pmean(x, axis: str):
    import jax
    return jax.lax.pmean(x, axis_name=axis)

def pmax(x, axis: str):
    import jax
    return jax.lax.pmax(x, axis_name=axis)


def all_gather(x, axis: str, *, tiled: bool = True, gather_axis: int = 0):
    import jax
    return jax.lax.all_gather(x, axis_name=axis, tiled=tiled,
                              axis=gather_axis)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0):
    import jax
    return jax.lax.psum_scatter(x, axis_name=axis,
                                scatter_dimension=scatter_axis, tiled=True)


def ppermute(x, axis: str, perm: Sequence[tuple]):
    import jax
    return jax.lax.ppermute(x, axis_name=axis, perm=list(perm))


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    import jax
    return jax.lax.all_to_all(x, axis_name=axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def axis_index(axis: str):
    import jax
    return jax.lax.axis_index(axis)


def ring_neighbors(axis: str, axis_size: int):
    """(forward, backward) permutation lists for a ring over `axis`."""
    fwd = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    bwd = [(i, (i - 1) % axis_size) for i in range(axis_size)]
    return fwd, bwd


# --------------------------------------------------------------------------
# Layer 2: host-level eager collective groups (reference-API compatible)


class CollectiveGroup:
    def __init__(self, name: str, world_size: int, rank: int,
                 devices: Optional[List] = None):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        import jax
        self.devices = devices if devices is not None else jax.devices()
        if len(self.devices) < world_size:
            raise ValueError(
                f"group {name}: world_size {world_size} exceeds visible "
                f"devices {len(self.devices)}")
        from jax.sharding import Mesh
        self.mesh = Mesh(np.array(self.devices[:world_size]), ("world",))

    @functools.lru_cache(maxsize=32)
    def _reduce_fn(self, op: str):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ray_tpu.parallel.jax_compat import shard_map

        red = {"sum": jax.lax.psum, "mean": jax.lax.pmean,
               "max": jax.lax.pmax, "min": jax.lax.pmin}[op]

        @jax.jit
        def fn(x):
            return shard_map(
                lambda v: red(v, "world"),
                mesh=self.mesh,
                in_specs=P("world"),
                out_specs=P("world"),
            )(x)
        return fn

    def allreduce(self, arrays, op: str = "sum"):
        """Eager allreduce of per-device arrays (stacked on dim 0)."""
        import jax.numpy as jnp
        stacked = jnp.stack(arrays) if isinstance(arrays, (list, tuple)) \
            else arrays
        return self._reduce_fn(op)(stacked)


_groups: Dict[str, CollectiveGroup] = {}


def init_collective_group(world_size: int, rank: int,
                          backend: str = "xla",
                          group_name: str = "default",
                          devices: Optional[List] = None) -> CollectiveGroup:
    """Reference-parity signature (collective.py:120). backend is always XLA
    on TPU; 'nccl'/'gloo' arguments are accepted and mapped for porting."""
    g = CollectiveGroup(group_name, world_size, rank, devices=devices)
    _groups[group_name] = g
    return g


def get_group(group_name: str = "default") -> CollectiveGroup:
    if group_name not in _groups:
        raise ValueError(f"collective group {group_name!r} not initialized")
    return _groups[group_name]


def destroy_collective_group(group_name: str = "default"):
    _groups.pop(group_name, None)


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    return get_group(group_name).allreduce(tensor, op)


def barrier(group_name: str = "default"):
    """A barrier over the group: an allreduce of a scalar."""
    import jax.numpy as jnp
    g = get_group(group_name)
    g.allreduce(jnp.zeros((g.world_size,)), "sum")


# --------------------------------------------------------------------------
# Multi-host bootstrap (SPMD island formation)


def initialize_distributed(coordinator_address: str, num_processes: int,
                           process_id: int,
                           local_device_ids: Optional[List[int]] = None):
    """Form a multi-host SPMD island: jax.distributed over DCN.

    This replaces the reference's torch dist.init_process_group TCP
    rendezvous (train/torch/config.py:113). The Train backend calls this on
    every gang worker with addresses brokered through GCS KV."""
    import jax
    kwargs: Dict[str, Any] = dict(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
