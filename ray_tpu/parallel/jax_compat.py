"""Version compat for jax APIs that moved between releases.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace, renaming ``check_rep`` -> ``check_vma`` and
replacing the ``auto`` (complement) axis set with an explicit
``axis_names`` (manual) set. Callers here use the new-style spelling;
this wrapper translates for older jax.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=None):
    try:
        from jax import shard_map as _sm  # jax >= 0.6
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        kw = {}
        if check_vma is not None:
            kw["check_rep"] = check_vma
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw["auto"] = auto
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kw)
    kw = {}
    if check_vma is not None:
        kw["check_vma"] = check_vma
    if axis_names is not None:
        kw["axis_names"] = set(axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)
