"""Mixture-of-Experts with expert parallelism over the ``ep`` mesh axis.

TPU-native GShard/Switch formulation (reference repo has no MoE engine —
SURVEY.md §2.6 marks EP absent; the design bar here is the public GShard/
Switch-Transformer dispatch): routing and dispatch are dense einsums over a
[tokens, experts, capacity] one-hot — no gather/scatter, fully static
shapes, so XLA tiles everything onto the MXU and inserts the all-to-alls
over ICI when the expert dimension is sharded P("ep", ...).

  gates    [S, E]     router softmax
  dispatch [S, E, C]  one-hot token->(expert, slot), capacity-dropped
  combine  [S, E, C]  dispatch * gate
  xin      = einsum('sec,sd->ecd', dispatch, x)     (all_to_all over ep)
  h        = act(einsum('ecd,edf->ecf', xin, w1))   (expert-sharded)
  out      = einsum('ecf,efd->ecd', h, w2)
  y        = einsum('sec,ecd->sd', combine, out)    (all_to_all back)

Top-1 (Switch) routing with the standard load-balance auxiliary loss.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoE(nn.Module):
    """Switch-style top-1 MoE feed-forward layer.

    Returns (y, aux_loss). Partition the expert params over ``ep`` via
    ``expert_sharding_rule`` (leading expert axis).
    """
    num_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    act: Callable = nn.gelu
    router_noise: float = 0.0

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        *lead, d = x.shape
        s = 1
        for n in lead:
            s *= n
        e = self.num_experts
        c = max(1, int(self.capacity_factor * s / e))
        xf = x.reshape(s, d)

        # ---- router (f32 for numerics, as in every public MoE impl)
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32))
        if self.router_noise > 0.0 and not deterministic:
            rng = self.make_rng("router")
            logits = logits + jax.random.uniform(
                rng, logits.shape, minval=1.0 - self.router_noise,
                maxval=1.0 + self.router_noise)
        gates = jax.nn.softmax(logits, axis=-1)            # [S, E]
        expert_idx = jnp.argmax(gates, axis=-1)            # [S]
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)

        # load-balance aux loss (Switch eq. 4): E * sum(frac_tokens * prob)
        density = onehot.mean(axis=0)
        prob_mean = gates.mean(axis=0)
        aux = e * jnp.sum(density * prob_mean)

        # position of each token within its expert (capacity slots)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [S, E]
        slot = pos.sum(axis=-1)                            # [S]
        keep = slot < c
        gate_val = (gates * onehot).sum(-1) * keep         # [S]
        dispatch = (onehot * keep[:, None])[:, :, None] * \
            jax.nn.one_hot(jnp.clip(slot, 0, c - 1), c,
                           dtype=jnp.float32)[:, None, :]  # [S, E, C]
        combine = dispatch * gate_val[:, None, None]

        # ---- expert computation, sharded over ep on the leading dim
        w1 = self.param(
            "experts_w1", nn.initializers.lecun_normal(), (e, d, self.d_ff),
            jnp.float32)
        w2 = self.param(
            "experts_w2", nn.initializers.lecun_normal(), (e, self.d_ff, d),
            jnp.float32)
        xin = jnp.einsum("sec,sd->ecd", dispatch.astype(self.dtype),
                         xf.astype(self.dtype))
        h = self.act(jnp.einsum("ecd,edf->ecf", xin, w1.astype(self.dtype)))
        out = jnp.einsum("ecf,efd->ecd", h, w2.astype(self.dtype))
        y = jnp.einsum("sec,ecd->sd", combine.astype(self.dtype), out)
        return y.reshape(*lead, d).astype(x.dtype), aux


def expert_sharding_rule(mesh, path: Tuple[str, ...], shape, spec):
    """Param-sharding hook: leaves named experts_* shard P("ep", ...) on the
    expert axis (compose with the default rules for other leaves)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    name = "/".join(str(p) for p in path)
    if "experts_" in name and spec.ep > 1 and shape and \
            shape[0] % spec.ep == 0:
        return NamedSharding(mesh, P("ep", *([None] * (len(shape) - 1))))
    return None
