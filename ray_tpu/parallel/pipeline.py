"""Pipeline parallelism: a microbatched circular-pipeline schedule over the
``pp`` mesh axis.

No reference analogue (the reference has no pipeline engine — SURVEY.md §2.6
marks PP absent); this is the TPU-native bar: the schedule is a single XLA
program — ``shard_map`` manual over ``pp`` (auto/GSPMD over dp/tp/sp inside),
activations rotate stage-to-stage with ``ppermute`` over ICI, and the
backward pass falls out of differentiating the forward scan (ppermute has a
transpose rule; the reverse scan IS the 1B phase, so the schedule is
GPipe-shaped: M forward ticks, then M backward ticks, bubble 2(S-1)).

Design constraints (standard for stacked-transformer PP):
  - all stages share one activation shape (uniform blocks);
  - per-stage parameters are stacked on a leading axis of size S =
    mesh.shape["pp"], sharded P("pp", ...) so each device group holds its
    stage's slice;
  - stage_fn is rematerialized (jax.checkpoint) so the M in-flight
    microbatch activations, not intermediates, bound memory.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def pipeline_apply(stage_fn: Callable, mesh, *, axis: str = "pp",
                   remat: bool = True) -> Callable:
    """Build ``apply(stage_params, microbatches) -> outputs``.

    stage_fn(params_slice, x) -> y with ``y.shape == x.shape`` — one stage's
    computation (e.g. L/S transformer blocks).
    stage_params: pytree whose leaves have leading axis S (stage-stacked).
    microbatches: [M, mb, ...] array; outputs: [M, mb, ...].

    The circular schedule runs T = M + S - 1 ticks. At tick t, stage 0
    ingests microbatch t (while it has any); every stage applies its slice
    and rotates its activation to the next stage. The last stage's outputs
    for microbatch m emerge at tick m + S - 1 and are broadcast back to all
    pp groups (psum of a one-hot selection) so downstream (loss) math is
    replicated over pp.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def _pipelined(stage_params, microbatches):
        s_idx = jax.lax.axis_index(axis)
        # jax.lax.axis_size doesn't exist on older jax; the mesh is
        # static and in scope, so take the size from it
        size = mesh.shape[axis]
        m = microbatches.shape[0]
        t_total = m + size - 1

        # local stage slice: leading axis is 1 on each pp group — squeeze
        local = jax.tree_util.tree_map(lambda a: a[0], stage_params)

        def tick(carry, t):
            buf = carry  # [mb, ...] activation entering this stage
            inject = microbatches[jnp.clip(t, 0, m - 1)]
            x_in = jnp.where(s_idx == 0, inject, buf)
            y = stage_fn(local, x_in)
            nxt = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % size) for i in range(size)])
            return nxt, y

        init = jnp.zeros_like(microbatches[0])
        _, ys = jax.lax.scan(tick, init, jnp.arange(t_total))
        # outputs for microbatch mb_i leave the LAST stage at tick
        # mb_i + size - 1; select them and replicate across pp
        outs = ys[size - 1:]  # [M, mb, ...] (valid only on last stage)
        # psum in f32: the one-hot selection makes this an exact broadcast,
        # and XLA-CPU's AllReducePromotion pass miscompiles bf16 all-reduce
        # (crashes in ChangeOpDataType) — f32 avoids it on every backend
        dt = outs.dtype
        is_last = (s_idx == size - 1).astype(jnp.float32)
        return jax.lax.psum(outs.astype(jnp.float32) * is_last,
                            axis).astype(dt)

    # manual over pp only; dp/tp/sp remain GSPMD-auto inside — XLA shards
    # the per-stage math over the other axes exactly as it would un-piped
    from ray_tpu.parallel.jax_compat import shard_map
    return shard_map(
        _pipelined, mesh=mesh,
        in_specs=(P(axis), P()), out_specs=P(),
        axis_names={axis}, check_vma=False)


def stack_stage_params(init_fn: Callable, rngs):
    """Initialize stage-parameter slices stacked on a leading axis via
    vmap (one rng per stage; the stage count is len(rngs))."""
    return jax.vmap(init_fn)(rngs)


def sequential_apply(stage_fn: Callable, stage_params, microbatches):
    """pp=1 semantics: run every stage in order on each microbatch — the
    parity oracle for tests (same math, no pipeline)."""
    num_stages = jax.tree_util.tree_leaves(stage_params)[0].shape[0]

    def run_one(x):
        def body(x, i):
            p = jax.tree_util.tree_map(lambda a: a[i], stage_params)
            return stage_fn(p, x), None
        out, _ = jax.lax.scan(body, x, jnp.arange(num_stages))
        return out
    return jax.vmap(run_one)(microbatches)
