"""Central validation of every ``@remote``/``.options()`` argument.

Role-equivalent to the reference's single-source-of-truth option table
(reference: python/ray/_private/ray_option_utils.py). TPU is a first-class
resource here: ``num_tpus`` sits beside ``num_cpus``/``num_gpus``, and TPU
topology constraints (slice types like ``"v5e-8"``) validate through
``accelerator_type``/``tpu_topology``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _Option:
    types: tuple
    validator: Optional[Callable[[Any], Optional[str]]] = None
    default: Any = None


def _nonneg(v):
    if v is not None and v < 0:
        return "must be >= 0"


def _pos(v):
    if v is not None and v <= 0:
        return "must be > 0"


def _retries(v):
    if v is not None and v < -1:
        return "must be >= -1 (-1 means infinite)"


def _resources_dict(v):
    if v is None:
        return None
    if not isinstance(v, dict):
        return "must be a dict"
    for k, val in v.items():
        if not isinstance(k, str):
            return f"resource name {k!r} must be a string"
        if k in ("CPU", "GPU", "TPU", "memory"):
            return f"use num_cpus/num_gpus/num_tpus/memory instead of resources[{k!r}]"
        if not isinstance(val, (int, float)) or val < 0:
            return f"resource {k!r} quantity must be a non-negative number"


_NUM = (int, float, type(None))

COMMON_OPTIONS: Dict[str, _Option] = {
    "num_cpus": _Option(_NUM, _nonneg),
    "num_gpus": _Option(_NUM, _nonneg),
    "num_tpus": _Option(_NUM, _nonneg),
    "memory": _Option(_NUM, _pos),
    "object_store_memory": _Option(_NUM, _pos),
    "resources": _Option((dict, type(None)), _resources_dict),
    "accelerator_type": _Option((str, type(None))),
    # TPU slice topology constraint, e.g. "v5e-8", "v4-32"; schedules the
    # task/actor onto a host of a matching slice.
    "tpu_topology": _Option((str, type(None))),
    "scheduling_strategy": _Option((str, object, type(None))),
    "runtime_env": _Option((dict, object, type(None))),
    "max_retries": _Option(_NUM, _retries),
    "retry_exceptions": _Option((bool, list, tuple, type(None))),
    "name": _Option((str, type(None))),
    "namespace": _Option((str, type(None))),
    "lifetime": _Option((str, type(None)),
                        lambda v: None if v in (None, "detached", "non_detached")
                        else "must be None, 'detached' or 'non_detached'"),
    "_metadata": _Option((dict, type(None))),
    "label_selector": _Option((dict, type(None))),
}

TASK_ONLY_OPTIONS: Dict[str, _Option] = {
    "num_returns": _Option(_NUM, lambda v: None if v is None or v >= 0 else "must be >= 0"),
    "max_calls": _Option(_NUM, _nonneg),
}

ACTOR_ONLY_OPTIONS: Dict[str, _Option] = {
    "max_restarts": _Option(_NUM, _retries),
    "max_task_retries": _Option(_NUM, _retries),
    "max_concurrency": _Option(_NUM, _pos),
    "max_pending_calls": _Option(_NUM, _retries),
    "get_if_exists": _Option((bool, type(None))),
    "concurrency_groups": _Option((dict, list, type(None))),
}

TASK_OPTIONS = {**COMMON_OPTIONS, **TASK_ONLY_OPTIONS}
ACTOR_OPTIONS = {**COMMON_OPTIONS, **ACTOR_ONLY_OPTIONS}


def validate_options(opts: Optional[Dict[str, Any]], is_actor: bool) -> Dict[str, Any]:
    if opts is None:
        return {}
    table = ACTOR_OPTIONS if is_actor else TASK_OPTIONS
    out = {}
    for k, v in opts.items():
        if k not in table:
            kind = "actors" if is_actor else "tasks"
            raise ValueError(f"Invalid option {k!r} for {kind}. Valid: {sorted(table)}")
        spec = table[k]
        if not isinstance(v, spec.types) and v is not None:
            raise TypeError(f"Option {k!r} must be of type {spec.types}, got {type(v)}")
        if spec.validator is not None:
            err = spec.validator(v)
            if err:
                raise ValueError(f"Option {k!r} {err}")
        out[k] = v
    return out


def resource_dict_from_options(opts: Dict[str, Any], is_actor: bool) -> Dict[str, float]:
    """Flatten options into the scheduler's resource demand map."""
    res: Dict[str, float] = {}
    num_cpus = opts.get("num_cpus")
    if num_cpus is None:
        # Reference semantics: tasks default to 1 CPU; actors require 1 CPU
        # for placement but hold 0 while running, so long-lived actors don't
        # starve the node (python/ray/_private/ray_option_utils.py defaults).
        num_cpus = 0 if is_actor else 1
    if num_cpus:
        res["CPU"] = float(num_cpus)
    for key, name in (("num_gpus", "GPU"), ("num_tpus", "TPU"), ("memory", "memory")):
        v = opts.get(key)
        if v:
            res[name] = float(v)
    for k, v in (opts.get("resources") or {}).items():
        if v:
            res[k] = float(v)
    return res
