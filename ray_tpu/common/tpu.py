"""TPU accelerator-type topology parsing shared by the raylet (chip
detection) and the autoscaler (slice capacity advertisement).

Reference analogue: python/ray/_private/resource_spec.py:268
(_autodetect_num_gpus) — the reference parses CUDA devices; here the
unit is the TPU accelerator-type string ("v4-32", "v5litepod-16").

One parsing rule, used everywhere: the "-N" suffix counts TensorCores
(2 per chip) on v2/v3/v4/v5p, and chips on v5e (v5litepod) / v6e.
Keeping a single helper means the autoscaler's advertised capacity
always matches what the slice's raylets will actually register.
"""

from __future__ import annotations

from typing import Optional, Tuple

# Generations whose accelerator-type suffix counts TensorCores, not chips.
_CORE_SUFFIX_GENS = ("v2", "v3", "v4", "v5p")


def slice_chips(accel: str) -> Optional[int]:
    """Total chips in the slice named by an accelerator type, or None if
    the string is unparseable."""
    gen, _, total_s = accel.partition("-")
    try:
        total = int(total_s)
    except ValueError:
        return None
    if gen in _CORE_SUFFIX_GENS:
        total //= 2
    return total


def max_chips_per_host(gen: str) -> int:
    """Physical per-host chip ceiling: 8 for v5e single-host (2x4
    topology), 4 for every other TPU-VM generation."""
    return 8 if (gen.startswith("v5lite") or gen == "v5e") else 4


def slice_topology(accel: str) -> Optional[Tuple[int, int]]:
    """(total_chips, hosts) for a slice, deriving hosts from the
    standard GCE TPU-VM layout: multi-host slices place 4 chips per
    host on every generation; a slice that fits the single-host ceiling
    (8 chips for v5e, 4 otherwise) is one host.
    """
    gen = accel.partition("-")[0]
    total = slice_chips(accel)
    if total is None:
        return None
    if total <= max_chips_per_host(gen):
        return total, 1
    return total, max(1, total // 4)
