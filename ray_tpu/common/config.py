"""System config registry, env-var overridable.

Equivalent in role to the reference's RAY_CONFIG system
(reference: src/ray/common/ray_config_def.h — 184 entries, each overridable by
``RAY_<name>`` env var or ``ray.init(_system_config=...)``). Here every entry is
declared once with a type and default, overridable by ``RTPU_<NAME>`` env vars
or ``ray_tpu.init(_system_config={...})``; the head process snapshots the
resolved config and distributes it to every worker via the control-plane
handshake so all processes agree.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict


def _env(name: str, typ, default):
    raw = os.environ.get(f"RTPU_{name.upper()}")
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() in ("1", "true", "yes")
    return typ(raw)


@dataclass
class SystemConfig:
    # ---- object store ----
    object_store_memory_bytes: int = 2 * 1024**3
    # objects smaller than this are inlined in the in-process memory store and
    # carried through the control plane rather than the shm store (analogue of
    # the reference's max_direct_call_object_size, ray_config_def.h)
    max_inline_object_size: int = 100 * 1024
    object_spilling_threshold: float = 0.8
    object_store_fallback_dir: str = ""
    # JSON spec for the spill backend (reference: object_spilling_config
    # in ray_config_def.h + _private/external_storage.py): e.g.
    # {"type": "smart_open", "params": {"uri_prefix": "s3://bkt/spill"}}
    object_spilling_config: str = ""
    # cap on in-flight inbound pull bytes as a fraction of store
    # capacity (reference: pull_manager.cc admission under pressure)
    pull_admission_fraction: float = 0.5
    # ---- scheduler ----
    scheduler_spread_threshold: float = 0.5
    worker_lease_timeout_s: float = 30.0
    max_pending_lease_requests_per_key: int = 10
    # ---- workers ----
    num_workers_soft_limit: int = -1  # -1: num_cpus
    idle_worker_kill_s: float = 300.0
    worker_start_timeout_s: float = 60.0
    # how long an executing task waits for an ObjectRef argument before
    # erroring (a freed/lost arg must not wedge the executor forever)
    arg_fetch_timeout_s: float = 300.0
    # max concurrent outbound object-pull streams a node serves for
    # LARGE objects; the surplus gets "busy" and retries against the
    # growing source set (tree broadcast — see raylet.handle_pull_object)
    object_serve_concurrency: int = 3
    object_serve_tree_min_bytes: int = 256 * 1024 * 1024
    prestart_workers: bool = True
    # ---- memory monitor / OOM protection (reference:
    # src/ray/common/memory_monitor.h + raylet/worker_killing_policy.h) ----
    memory_monitor_enabled: bool = True
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 500
    # ---- fault tolerance ----
    task_max_retries_default: int = 3
    actor_max_restarts_default: int = 0
    lineage_max_bytes: int = 1024**3
    health_check_period_s: float = 1.0
    # Death window. The reference's GCS declares death only after a
    # FAILURE STREAK of active probes (health_check_period 3s x
    # failure_threshold 5 on top of a 10s probe timeout — i.e. tens of
    # seconds), precisely so load spikes don't read as deaths. 10s here
    # killed 50 healthy-but-starved raylets during the 1 GiB broadcast
    # on the single-core CI box.
    health_check_timeout_s: float = 30.0
    # a raylet whose liveness thread beats but whose event loop reports
    # lag beyond this is treated as dead (wedged loop = dead node; busy
    # loop = alive). See raylet._start_liveness_thread.
    loop_stall_death_s: float = 60.0
    # default preemption grace window (TPU spot semantics: notice →
    # drain → host reclaim); a notice may carry its own grace_s
    preemption_grace_s: float = 10.0
    # how long a revoked lease waits for the owner's drain ack
    # (release_lease with inflight=0) before being force-reclaimed
    lease_revoke_ack_timeout_s: float = 5.0
    # ---- control plane ----
    gcs_port: int = 0  # 0 = auto
    rpc_connect_timeout_s: float = 10.0
    pubsub_poll_timeout_s: float = 30.0
    # ---- TPU ----
    tpu_chips_per_host: int = -1  # -1: autodetect
    tpu_visible_chips_env: str = "TPU_VISIBLE_CHIPS"
    # persistent XLA compilation cache shared across workers (no reference
    # analogue; new subsystem per SURVEY.md §7 "Compilation management")
    compilation_cache_dir: str = ""
    # ---- metrics/events ----
    metrics_report_period_s: float = 5.0
    event_log_enabled: bool = True

    def apply_env_overrides(self):
        for f in fields(self):
            cur = getattr(self, f.name)
            setattr(self, f.name, _env(f.name, type(cur), cur))
        return self

    def update(self, overrides: Dict[str, Any]):
        for k, v in (overrides or {}).items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown system config key: {k}")
            setattr(self, k, v)
        return self

    def to_json(self) -> str:
        return json.dumps({f.name: getattr(self, f.name) for f in fields(self)})

    @classmethod
    def from_json(cls, s: str) -> "SystemConfig":
        cfg = cls()
        cfg.update(json.loads(s))
        return cfg


_global_config: SystemConfig | None = None


def global_config() -> SystemConfig:
    global _global_config
    if _global_config is None:
        _global_config = SystemConfig().apply_env_overrides()
    return _global_config


def set_global_config(cfg: SystemConfig):
    global _global_config
    _global_config = cfg
