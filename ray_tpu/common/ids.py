"""Binary unique identifiers for tasks, objects, actors, nodes, jobs.

Design follows the reference's ID specification (reference:
src/ray/design_docs/id_specification.md and src/ray/common/id.h) in *semantics*
— ObjectIDs are derived from the creating TaskID plus a return/put index so
lineage can be recomputed — but the layout is simplified for this runtime:

  JobID      : 4 bytes
  ActorID    : 12 bytes  (8 random + 4 job)
  TaskID     : 16 bytes  (8 random/derived + 8 parent info)
  ObjectID   : 24 bytes  (16 task + 4 index + 4 flags)
  NodeID     : 16 bytes  (random)
  WorkerID   : 16 bytes  (random)
  PlacementGroupID : 12 bytes

All IDs are immutable, hashable, and hex-serializable.
"""

from __future__ import annotations

import os
import struct


class BaseID:
    SIZE = 16
    __slots__ = ("_bytes",)

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = bytes(binary)

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, value: int):
        return cls(struct.pack("<I", value))

    def int(self) -> int:
        return struct.unpack("<I", self._bytes)[0]


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(8) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[8:])


class PlacementGroupID(BaseID):
    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(8) + job_id.binary())


class TaskID(BaseID):
    SIZE = 16

    @classmethod
    def for_driver(cls, job_id: JobID):
        return cls(b"\xff" * 8 + b"\x00" * 4 + job_id.binary())

    @classmethod
    def for_task(cls, parent: "TaskID"):
        return cls(os.urandom(8) + parent.binary()[:8])

    @classmethod
    def for_actor_task(cls, actor_id: ActorID, seq_no: int):
        return cls(actor_id.binary()[:8] + struct.pack("<q", seq_no))


class ObjectID(BaseID):
    SIZE = 24
    MAX_INDEX = 2**31

    # flags
    _PUT = 1
    _RETURN = 0

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        return cls(task_id.binary() + struct.pack("<iI", put_index, cls._PUT))

    @classmethod
    def for_return(cls, task_id: TaskID, return_index: int):
        return cls(task_id.binary() + struct.pack("<iI", return_index, cls._RETURN))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:16])

    def index(self) -> int:
        return struct.unpack("<i", self._bytes[16:20])[0]

    def is_put(self) -> bool:
        return struct.unpack("<I", self._bytes[20:24])[0] == self._PUT


ObjectRefID = ObjectID  # alias
