"""`ray-tpu` command line.

Reference analogue: python/ray/scripts/scripts.py (`ray start/stop/
status/memory/timeline`) + dashboard/modules/job/cli.py (`ray job ...`).
argparse-based (zero extra deps).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time


def _connect(address=None):
    import ray_tpu
    ray_tpu.init(address=address or os.environ.get("RTPU_ADDRESS"),
                 ignore_reinit_error=True)
    return ray_tpu


def cmd_start(args):
    import ray_tpu
    if args.head:
        ctx = ray_tpu.init(
            num_cpus=args.num_cpus, num_tpus=args.num_tpus,
            resources=json.loads(args.resources)
            if args.resources else None)
        print(f"started head; GCS at {ctx['gcs_address']}")
        print(f"export RTPU_ADDRESS={ctx['gcs_address']}")
        if args.ray_client_server_port is not None:
            from ray_tpu.util.client.server import ClientServer
            srv = ClientServer(port=args.ray_client_server_port)
            print(f"ray:// client server on port {srv.port} "
                  f"(connect with ray_tpu.init('ray://<host>:{srv.port}'))")
            if not args.block:
                # the server lives on daemon threads in THIS process; if
                # the CLI exits, clients get connection-refused while the
                # cluster subprocesses keep running
                print("note: --ray-client-server-port implies --block")
                args.block = True
        if args.dashboard:
            from ray_tpu.dashboard.dashboard import start_dashboard
            port = start_dashboard(port=args.dashboard_port)
            print(f"dashboard at http://127.0.0.1:{port}")
        if args.block:
            print("blocking; Ctrl-C to stop")
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                pass
            ray_tpu.shutdown()
    else:
        if not args.address:
            sys.exit("--address required for worker nodes")
        from ray_tpu._private import node as node_mod
        info = node_mod.add_node(
            node_mod.new_session_dir(), args.address,
            resources={"CPU": args.num_cpus or 1,
                       **({"TPU": args.num_tpus}
                          if args.num_tpus else {})})
        print(f"started worker node {info['node_id'][:8]} "
              f"against {args.address}")
        if args.block:
            try:
                while True:
                    time.sleep(3600)
            except KeyboardInterrupt:
                info["proc"].terminate()


def cmd_stop(args):
    # reference `ray stop`: kill every framework process on this machine
    patterns = ["ray_tpu._private.gcs_main",
                "ray_tpu._private.raylet_main",
                "ray_tpu._private.default_worker"]
    n = 0
    for pat in patterns:
        r = subprocess.run(["pkill", "-f", pat], capture_output=True)
        n += int(r.returncode == 0)
    print(f"stopped ({n} process groups signalled)")


def cmd_status(args):
    rt = _connect(args.address)
    from ray_tpu.experimental.state import summarize_cluster
    s = summarize_cluster()
    print(json.dumps(s, indent=2, default=str))


def cmd_memory(args):
    rt = _connect(args.address)
    w = rt._worker_mod.global_worker()
    refs = w.reference_counter.debug_dump() if hasattr(
        w.reference_counter, "debug_dump") else {}
    print(json.dumps({"local_references": len(refs) if refs else 0},
                     indent=2))


def cmd_timeline(args):
    rt = _connect(args.address)
    from ray_tpu.util.timeline import timeline_dump
    out = args.output or "timeline.json"
    with open(out, "w") as f:
        json.dump(timeline_dump(), f)
    print(f"wrote {out}")


def cmd_job_submit(args):
    from ray_tpu.job_submission import JobSubmissionClient
    client = JobSubmissionClient(args.address)
    job_id = client.submit_job(
        entrypoint=" ".join(args.entrypoint),
        runtime_env=json.loads(args.runtime_env)
        if args.runtime_env else None)
    print(f"submitted job {job_id}")
    if args.wait:
        status = client.wait_until_finish(job_id, timeout=args.timeout)
        print(f"job {job_id}: {status}")
        print(client.get_job_logs(job_id))
        sys.exit(0 if status == "SUCCEEDED" else 1)


def cmd_job_status(args):
    from ray_tpu.job_submission import JobSubmissionClient
    print(JobSubmissionClient(args.address).get_job_status(args.job_id))


def cmd_job_logs(args):
    from ray_tpu.job_submission import JobSubmissionClient
    print(JobSubmissionClient(args.address).get_job_logs(args.job_id))


def cmd_job_list(args):
    from ray_tpu.job_submission import JobSubmissionClient
    for j in JobSubmissionClient(args.address).list_jobs():
        print(f"{j.get('job_id')}\t{j.get('status')}\t"
              f"{j.get('entrypoint')}")


def cmd_job_stop(args):
    from ray_tpu.job_submission import JobSubmissionClient
    JobSubmissionClient(args.address).stop_job(args.job_id)
    print(f"stopped {args.job_id}")


def _print_table(rows, cols):
    """Aligned plain-text table (no deps); values stringified, None
    printed as '-'."""
    def cell(r, c):
        v = r.get(c)
        if v is None:
            return "-"
        if isinstance(v, float):
            return f"{v:.6g}"
        if isinstance(v, (dict, list)):
            return json.dumps(v)
        return str(v)
    table = [[cell(r, c) for c in cols] for r in rows]
    widths = [max(len(c), *(len(t[i]) for t in table)) if table
              else len(c) for i, c in enumerate(cols)]
    print("  ".join(c.ljust(w) for c, w in zip(cols, widths)))
    for t in table:
        print("  ".join(v.ljust(w) for v, w in zip(t, widths)))


_LIST_COLUMNS = {
    "tasks": ["task_id", "name", "state", "attempt", "node_id",
              "worker_pid", "duration_s", "error"],
    "objects": ["object_id", "size_bytes", "pinned", "spilled",
                "locations", "owner"],
    "actors": ["actor_id", "class_name", "state", "name",
               "num_restarts", "node_id"],
    "nodes": ["node_id", "alive", "draining", "is_head", "resources",
              "available"],
    "jobs": ["job_id", "status", "namespace", "driver_pid"],
    "placement-groups": ["pg_id", "state", "strategy", "name"],
}


def cmd_list(args):
    """`ray-tpu list tasks|objects|actors|nodes|jobs|placement-groups`
    (reference: `ray list ...` backed by the state API): paginated,
    server-side filtered listings."""
    _connect(args.address)
    from ray_tpu.experimental.state import api as state
    filters = {}
    for f in args.filter or ():
        k, sep, v = f.partition("=")
        if not sep:
            sys.exit(f"--filter wants key=value, got {f!r}")
        filters[k] = v
    if getattr(args, "state", None):
        filters["state"] = args.state
    fn = {"tasks": state.list_tasks, "objects": state.list_objects,
          "actors": state.list_actors, "nodes": state.list_nodes,
          "jobs": state.list_jobs,
          "placement-groups": state.list_placement_groups}[args.resource]
    rows = fn(filters=filters or None, limit=args.limit)
    if args.json:
        print(json.dumps(list(rows), indent=2, default=str))
    else:
        cols = _LIST_COLUMNS[args.resource]
        short = {"task_id", "actor_id", "node_id", "object_id", "pg_id"}
        view = [{c: (str(r.get(c))[:16] if c in short and r.get(c)
                     else r.get(c)) for c in cols} for r in rows]
        _print_table(view, cols)
    total = rows.total if rows.total is not None else len(rows)
    note = f"{len(rows)} shown / {total} matched"
    if rows.dropped:
        note += f" ({rows.dropped} evicted past the table cap)"
    if rows.next_token:
        note += " — more available (raise --limit)"
    print(note, file=sys.stderr)


def cmd_summary(args):
    """`ray-tpu summary tasks`: per-function aggregation computed
    GCS-side over the bounded task table."""
    _connect(args.address)
    from ray_tpu.experimental.state import api as state
    s = state.summarize_tasks()
    rows = [{"name": a["name"], "count": a["count"],
             "mean_duration_s": a.get("mean_duration_s"),
             **{st: a["by_state"].get(st, 0)
                for st in ("RUNNING", "FINISHED", "FAILED")}}
            for a in s.get("summary", ())]
    _print_table(rows, ["name", "count", "RUNNING", "FINISHED",
                        "FAILED", "mean_duration_s"])
    print(f"table: {s.get('total', 0)} tracked, "
          f"{s.get('dropped', 0)} evicted, "
          f"{s.get('events_dropped', 0)} events dropped at source",
          file=sys.stderr)


def cmd_trace_list(args):
    """`ray-tpu trace list`: recent traces from the GCS trace table."""
    _connect(args.address)
    from ray_tpu.experimental.state import api as state
    rows = state.list_traces(limit=args.limit)
    view = sorted(rows, key=lambda r: -(r.get("start_ts") or 0))
    for r in view:
        r["start"] = time.strftime(
            "%H:%M:%S", time.localtime(r.get("start_ts") or 0))
    _print_table(view, ["trace_id", "root", "spans", "start",
                        "duration_s", "status"])
    if rows.dropped:
        print(f"{rows.dropped} spans evicted past the table cap",
              file=sys.stderr)


def cmd_trace_show(args):
    """`ray-tpu trace show <id>`: the span tree, indented; --chrome
    writes a chrome://tracing document merged with any XLA device
    spans (tpu_profiler) on the same wall-clock axis."""
    _connect(args.address)
    from ray_tpu._private import tracing
    from ray_tpu.experimental.state import api as state
    doc = state.get_trace(args.trace_id)
    spans = doc.get("spans") or []
    if not spans:
        sys.exit(f"no spans for trace {args.trace_id!r}")
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(tracing.export_chrome(spans), f)
        print(f"wrote {args.chrome} ({len(spans)} spans)")
        return
    if args.json:
        print(json.dumps(doc, indent=2, default=str))
        return
    by_parent = {}
    ids = {s.get("span_id") for s in spans}
    for s in spans:
        p = s.get("parent_span_id")
        key = p if p in ids else None
        by_parent.setdefault(key, []).append(s)
    t0 = min(s["start_ts"] for s in spans if s.get("start_ts"))

    def walk(parent, depth):
        for s in sorted(by_parent.get(parent, ()),
                        key=lambda x: x.get("start_ts") or 0):
            dur = ((s.get("end_ts") or 0) - (s.get("start_ts") or 0))
            off = (s.get("start_ts") or t0) - t0
            mark = "" if s.get("status") in (None, "ok") \
                else f"  [{s['status'].upper()}]"
            print(f"{'  ' * depth}{s.get('name')}  "
                  f"+{off * 1e3:.2f}ms {dur * 1e3:.2f}ms "
                  f"({s.get('phase')}){mark}")
            walk(s.get("span_id"), depth + 1)

    walk(None, 0)
    ok, detail = tracing.tree_complete(spans)
    print(f"tree: {'complete' if ok else 'INCOMPLETE'} — {detail}",
          file=sys.stderr)


def cmd_trace_critical_path(args):
    """`ray-tpu trace critical-path <id>`: attribute the trace's wall
    time to named phases (queue/schedule/dispatch/transfer/execute/
    deserialize) with the deepest-span sweep; --gameday-p99 aggregates
    the published game-day report's p99 cohort instead."""
    _connect(args.address)
    from ray_tpu._private import tracing
    from ray_tpu.experimental.state import api as state
    if args.trace_id:
        doc = state.get_trace(args.trace_id)
        spans = doc.get("spans") or []
        if not spans:
            sys.exit(f"no spans for trace {args.trace_id!r}")
        cp = tracing.critical_path(spans)
        total = cp["total_s"] or 1.0
        print(f"trace {args.trace_id}: {cp['total_s'] * 1e3:.2f}ms "
              f"wall, {cp['attributed_frac'] * 100:.1f}% attributed")
        _print_table(
            [{"phase": k, "ms": round(v * 1e3, 3),
              "pct": round(100 * v / total, 1)}
             for k, v in cp["phases"].items()],
            ["phase", "ms", "pct"])
        if args.segments:
            base = cp["segments"][0]["t0"] if cp["segments"] else 0.0
            for seg in cp["segments"]:
                off_ms = (seg["t0"] - base) * 1e3
                dur_ms = (seg["t1"] - seg["t0"]) * 1e3
                print(f"  +{off_ms:8.2f}ms  {dur_ms:8.2f}ms  "
                      f"{seg['phase']:<12} {seg['name']}")
        return
    # --gameday-p99: the published report names the slowest requests;
    # aggregate their traces (where does the tail spend its time?)
    from ray_tpu.gameday import store as gd_store
    report = gd_store.load_report()
    if not report:
        sys.exit("no trace id given and no game-day report published")
    slowest = report.get("slowest") or []
    traces = []
    for entry in slowest:
        tid = entry.get("trace_id")
        if not tid:
            continue
        spans = state.get_trace(tid).get("spans") or []
        if spans:
            traces.append(spans)
    if not traces:
        sys.exit("the published report's slowest requests have no "
                 "stored traces (sampled out or evicted)")
    agg = tracing.aggregate_critical_path(traces)
    print(f"{agg['traces']} tail traces, "
          f"{agg['total_s'] * 1e3:.1f}ms total")
    _print_table(
        [{"phase": k, "ms": round(v * 1e3, 3),
          "pct": round(100 * agg.get("phase_frac", {}).get(k, 0), 1)}
         for k, v in agg["phases"].items()],
        ["phase", "ms", "pct"])


def cmd_events(args):
    _connect(args.address)
    from ray_tpu.experimental.state import api as state
    for e in state.list_cluster_events(limit=args.limit,
                                       severity=args.severity):
        ts = time.strftime("%H:%M:%S",
                           time.localtime(e.get("timestamp", 0)))
        print(f"[{ts}] {e.get('severity', ''):7} "
              f"{e.get('source', ''):7} {e.get('label', '')}: "
              f"{e.get('message', '')}")


def cmd_grafana(args):
    from ray_tpu.dashboard.grafana import write_dashboards
    for path in write_dashboards(args.out):
        print(path)


def cmd_up(args):
    from ray_tpu.autoscaler.commands import create_or_update_cluster
    state = create_or_update_cluster(args.config_file)
    print(f"cluster {state['cluster_name']!r} up "
          f"({len(state.get('nodes', {}))} worker nodes)")
    head = state.get("head") or {}
    if head.get("gcs_address"):
        print(f"export RTPU_ADDRESS={head['gcs_address']}")
    if state.get("bootstrap"):
        print(state["bootstrap"])


def cmd_down(args):
    from ray_tpu.autoscaler.commands import teardown_cluster
    n = teardown_cluster(args.config_file)
    print(f"tore down {n} nodes")


def _serve_connect(args):
    import ray_tpu
    ray_tpu.init(address=args.address, ignore_reinit_error=True)


def cmd_serve_run(args):
    """`ray-tpu serve run module:app` (reference: serve/scripts.py run)."""
    _serve_connect(args)
    from ray_tpu.serve.schema import ServeApplicationSchema, build_app
    from ray_tpu.serve.api import run as serve_run
    sys.path.insert(0, os.getcwd())
    schema = ServeApplicationSchema(
        name=args.name, import_path=args.import_path,
        route_prefix=args.route_prefix)
    app = build_app(schema)
    serve_run(app, name=args.name, route_prefix=args.route_prefix,
              http_port=args.port)
    print(f"app {args.name!r} deployed from {args.import_path} "
          f"on port {args.port}")
    if args.blocking:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass


def cmd_serve_deploy(args):
    _serve_connect(args)
    import yaml
    from ray_tpu.serve.schema import deploy_config
    sys.path.insert(0, os.getcwd())
    with open(args.config_file) as f:
        config = yaml.safe_load(f)
    names = deploy_config(config)
    print(f"deployed applications: {', '.join(names)}")


def cmd_serve_status(args):
    _serve_connect(args)
    from ray_tpu import serve
    print(json.dumps({"applications": serve.list_applications(),
                      "deployments": serve.status()}, indent=2,
                     default=str))


def cmd_serve_shutdown(args):
    _serve_connect(args)
    from ray_tpu import serve
    serve.shutdown()
    print("serve shut down")


def cmd_gameday_list(args):
    from ray_tpu.gameday import builtin_scenarios
    for name, desc in sorted(builtin_scenarios().items()):
        print(f"{name:16} {desc}")


def cmd_gameday_run(args):
    """`ray-tpu gameday run <scenario>`: one replayable game day on a
    fresh local cluster — open-loop load + seeded faults + timed
    actions, graded client-side and reconciled against the server
    (docs/GAMEDAY.md). Exit code 0 iff the scenario passed."""
    from ray_tpu.gameday import load_scenario, run_scenario
    sc = load_scenario(args.scenario, seed=args.seed)
    result = run_scenario(sc, scale=args.scale,
                          dashboard_port=None if args.no_dashboard
                          else 18470)
    report = result.report
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(f"scenario {report['scenario']} @ seed {report['seed']} "
              f"(scale {args.scale}) — "
              f"{'PASSED' if report['passed'] else 'FAILED'}")
        cols = ["phase", "total", "admitted", "shed", "failed",
                "p50_ms", "p99_ms", "p999_ms", "max_ms"]
        rows = [{"phase": n, **p}
                for n, p in report.get("phases", {}).items()]
        rows.append({"phase": "OVERALL", **report.get("overall", {})})
        _print_table(rows, cols)
        slo = report.get("slo", {})
        print(f"availability burn {slo.get('availability_burn')} "
              f"(target {slo.get('availability_target')})"
              + (f"; latency burn {slo.get('latency_burn')} "
                 f"(target p99 ≤ {slo.get('latency_target_ms')}ms)"
                 if "latency_burn" in slo else ""))
        for c in report.get("reconciliation", {}).get("checks", []):
            mark = "ok " if c["ok"] else "FAIL"
            print(f"  [{mark}] {c['name']}: {c['detail']}")
        for err in report.get("action_errors", []):
            print(f"  [FAIL] action: {err}")
        if report.get("chaos_fired"):
            print(f"  chaos fired: {report['chaos_fired']}")
    sys.exit(0 if report.get("passed") else 1)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="ray-tpu",
        description="TPU-native distributed compute framework")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("start", help="start head or worker node")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="GCS address for worker nodes")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--resources", help="JSON dict of extra resources")
    sp.add_argument("--dashboard", action="store_true")
    sp.add_argument("--dashboard-port", type=int, default=8265)
    sp.add_argument("--ray-client-server-port", type=int, default=None,
                    help="serve ray:// clients on this port (0 = pick)")
    sp.add_argument("--block", action="store_true")
    sp.set_defaults(func=cmd_start)

    sp = sub.add_parser("stop", help="stop all local processes")
    sp.set_defaults(func=cmd_stop)

    sp = sub.add_parser("status", help="cluster summary")
    sp.add_argument("--address", default=None)
    sp.set_defaults(func=cmd_status)

    sp = sub.add_parser("memory", help="reference/memory summary")
    sp.add_argument("--address", default=None)
    sp.set_defaults(func=cmd_memory)

    sp = sub.add_parser("timeline", help="dump chrome trace")
    sp.add_argument("--address", default=None)
    sp.add_argument("--output", default=None)
    sp.set_defaults(func=cmd_timeline)

    jp = sub.add_parser("job", help="job submission")
    jsub = jp.add_subparsers(dest="job_command", required=True)
    sp = jsub.add_parser("submit")
    sp.add_argument("--address", default=None)
    sp.add_argument("--runtime-env", default=None)
    sp.add_argument("--wait", action="store_true")
    sp.add_argument("--timeout", type=float, default=600.0)
    sp.add_argument("entrypoint", nargs="+")
    sp.set_defaults(func=cmd_job_submit)
    for name, fn in (("status", cmd_job_status), ("logs", cmd_job_logs),
                     ("stop", cmd_job_stop)):
        sp = jsub.add_parser(name)
        sp.add_argument("--address", default=None)
        sp.add_argument("job_id")
        sp.set_defaults(func=fn)
    sp = jsub.add_parser("list")
    sp.add_argument("--address", default=None)
    sp.set_defaults(func=cmd_job_list)

    sp = sub.add_parser(
        "list", help="paginated state listings (tasks/objects/...)")
    sp.add_argument("resource",
                    choices=["tasks", "objects", "actors", "nodes",
                             "jobs", "placement-groups"])
    sp.add_argument("--address", default=None)
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--state", default=None,
                    help="shorthand for --filter state=...")
    sp.add_argument("--filter", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="server-side equality filter (repeatable)")
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(func=cmd_list)

    sp = sub.add_parser("summary", help="aggregated state summaries")
    sp.add_argument("what", choices=["tasks"])
    sp.add_argument("--address", default=None)
    sp.set_defaults(func=cmd_summary)

    tp = sub.add_parser(
        "trace", help="distributed traces (docs/TRACING.md)")
    tsub = tp.add_subparsers(dest="trace_command", required=True)
    sp = tsub.add_parser("list", help="recent traces")
    sp.add_argument("--address", default=None)
    sp.add_argument("--limit", type=int, default=50)
    sp.set_defaults(func=cmd_trace_list)
    sp = tsub.add_parser("show", help="span tree of one trace")
    sp.add_argument("trace_id")
    sp.add_argument("--address", default=None)
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--chrome", default=None, metavar="OUT.json",
                    help="write a chrome://tracing doc (merged with "
                         "XLA device spans on one time axis)")
    sp.set_defaults(func=cmd_trace_show)
    sp = tsub.add_parser(
        "critical-path",
        help="attribute a trace's wall time to named phases")
    sp.add_argument("trace_id", nargs="?", default=None)
    sp.add_argument("--address", default=None)
    sp.add_argument("--segments", action="store_true",
                    help="print the attributed time slices")
    sp.add_argument("--gameday-p99", action="store_true",
                    help="aggregate the published game-day report's "
                         "slowest requests instead of one trace")
    sp.set_defaults(func=cmd_trace_critical_path)

    sp = sub.add_parser("events", help="structured cluster events")
    sp.add_argument("--address", default=None)
    sp.add_argument("--limit", type=int, default=100)
    sp.add_argument("--severity", default=None)
    sp.set_defaults(func=cmd_events)

    sp = sub.add_parser(
        "grafana",
        help="generate importable Grafana dashboards for /metrics")
    sp.add_argument("--out", default="./grafana_dashboards")
    sp.set_defaults(func=cmd_grafana)

    sp = sub.add_parser("up", help="create/update a cluster from YAML")
    sp.add_argument("config_file")
    sp.set_defaults(func=cmd_up)
    sp = sub.add_parser("down", help="tear down a cluster from YAML")
    sp.add_argument("config_file")
    sp.set_defaults(func=cmd_down)

    svp = sub.add_parser("serve", help="model serving")
    ssub = svp.add_subparsers(dest="serve_command", required=True)
    sp = ssub.add_parser("run", help="deploy module:app and block")
    sp.add_argument("--address", default=None)
    sp.add_argument("--name", default="default")
    sp.add_argument("--route-prefix", default="/")
    sp.add_argument("--port", type=int, default=8000)
    sp.add_argument("--blocking", action="store_true", default=True)
    sp.add_argument("--non-blocking", dest="blocking",
                    action="store_false")
    sp.add_argument("import_path")
    sp.set_defaults(func=cmd_serve_run)
    sp = ssub.add_parser("deploy", help="deploy a YAML config file")
    sp.add_argument("--address", default=None)
    sp.add_argument("config_file")
    sp.set_defaults(func=cmd_serve_deploy)
    sp = ssub.add_parser("status")
    sp.add_argument("--address", default=None)
    sp.set_defaults(func=cmd_serve_status)
    sp = ssub.add_parser("shutdown")
    sp.add_argument("--address", default=None)
    sp.set_defaults(func=cmd_serve_shutdown)

    gdp = sub.add_parser(
        "gameday",
        help="replayable production-traffic SLO scenarios")
    gsub = gdp.add_subparsers(dest="gameday_command", required=True)
    sp = gsub.add_parser("run", help="run a scenario on a fresh "
                                     "local cluster")
    sp.add_argument("scenario",
                    help="builtin name (see `gameday list`) or a JSON "
                         "spec path")
    sp.add_argument("--seed", type=int, default=None,
                    help="override the scenario seed (same seed = "
                         "same arrivals + fault schedule)")
    sp.add_argument("--scale", type=float, default=1.0,
                    help="stretch/shrink phase durations")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--no-dashboard", action="store_true",
                    help="skip the dashboard + Prometheus cross-check")
    sp.set_defaults(func=cmd_gameday_run)
    sp = gsub.add_parser("list", help="list builtin scenarios")
    sp.set_defaults(func=cmd_gameday_list)

    # `ray-tpu lint ...` delegates argv wholesale to the rtpulint CLI
    # (ray_tpu/analysis/cli.py) so `ray-tpu lint` and `python -m
    # ray_tpu.analysis` stay one surface — docs/STATIC_ANALYSIS.md
    sp = sub.add_parser(
        "lint", add_help=False,
        help="project-aware static analysis (rtpulint; see "
             "docs/STATIC_ANALYSIS.md)")
    sp.set_defaults(func=None)

    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "lint":
        from ray_tpu.analysis.cli import main as lint_main
        sys.exit(lint_main(argv[1:]))

    args = p.parse_args(argv)
    args.func(args)


if __name__ == "__main__":
    main()
