"""Experiment-directory syncing to external storage.

Reference: tune/syncer.py (SyncConfig:88, Syncer:157, SyncerCallback:575).
Only local/file:// targets have a built-in backend in this image (no cloud
SDKs); the Syncer ABC is the seam for fsspec/cloud backends.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import time
from typing import List, Optional

from ray_tpu.tune.logger import Callback


@dataclasses.dataclass
class SyncConfig:
    upload_dir: Optional[str] = None  # file:// or plain path
    syncer: Optional["Syncer"] = None  # None = pick by upload_dir scheme
    sync_period: float = 300.0


class Syncer:
    def sync_up(self, local_dir: str, remote_dir: str) -> bool:
        raise NotImplementedError

    def sync_down(self, remote_dir: str, local_dir: str) -> bool:
        raise NotImplementedError

    def delete(self, remote_dir: str) -> bool:
        raise NotImplementedError


def _strip_scheme(uri: str) -> str:
    if uri.startswith("file://"):
        return uri[len("file://"):]
    if "://" in uri:
        raise ValueError(
            f"no built-in syncer for {uri!r} — pass SyncConfig(syncer=...) "
            "with a custom Syncer for cloud storage")
    return uri


class LocalSyncer(Syncer):
    """Recursive copy for local / file:// targets."""

    def sync_up(self, local_dir: str, remote_dir: str) -> bool:
        dst = _strip_scheme(remote_dir)
        os.makedirs(dst, exist_ok=True)
        shutil.copytree(local_dir, dst, dirs_exist_ok=True)
        return True

    def sync_down(self, remote_dir: str, local_dir: str) -> bool:
        src = _strip_scheme(remote_dir)
        os.makedirs(local_dir, exist_ok=True)
        shutil.copytree(src, local_dir, dirs_exist_ok=True)
        return True

    def delete(self, remote_dir: str) -> bool:
        shutil.rmtree(_strip_scheme(remote_dir), ignore_errors=True)
        return True


def get_syncer(sync_config: Optional[SyncConfig]) -> Optional[Syncer]:
    if sync_config is None or sync_config.upload_dir is None:
        return None
    return sync_config.syncer or LocalSyncer()


class SyncerCallback(Callback):
    """Syncs the experiment dir to upload_dir: throttled on results, always
    on trial completion and experiment end."""

    def __init__(self, sync_config: SyncConfig):
        self._config = sync_config
        self._syncer = get_syncer(sync_config)
        self._experiment_dir: Optional[str] = None
        self._last_sync = 0.0

    def setup(self, experiment_dir: Optional[str] = None):
        self._experiment_dir = experiment_dir

    def _target(self) -> Optional[str]:
        if self._experiment_dir is None or self._syncer is None:
            return None
        name = os.path.basename(self._experiment_dir.rstrip("/"))
        base = self._config.upload_dir.rstrip("/")
        return f"{base}/{name}"

    def _sync(self, force: bool = False):
        target = self._target()
        if target is None:
            return
        now = time.time()
        if not force and now - self._last_sync < self._config.sync_period:
            return
        self._syncer.sync_up(self._experiment_dir, target)
        self._last_sync = now

    def on_trial_result(self, trial, result):
        self._sync(force=False)

    def on_trial_complete(self, trial):
        self._sync(force=True)

    def on_experiment_end(self, trials: List) -> None:
        self._sync(force=True)
