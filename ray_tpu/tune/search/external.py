"""Soft-gated adapters for external optimization libraries.

Reference: tune/search/hyperopt/hyperopt_search.py,
search/optuna/optuna_search.py — both soft-import their backing library.
Neither ships in this image; when absent these adapters raise an
ImportError pointing at the native equivalents (TPESearcher /
BayesOptSearch), which cover the same capability without the dependency.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ray_tpu.tune.sample import resolve
from ray_tpu.tune.search._space import flatten_space, unflatten
from ray_tpu.tune.search.searcher import Searcher


class HyperOptSearch(Searcher):
    """hyperopt-backed TPE (requires the `hyperopt` package)."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 num_samples: Optional[int] = None,
                 seed: Optional[int] = None):
        try:
            import hyperopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "HyperOptSearch requires the `hyperopt` package, which is "
                "not installed. Use ray_tpu.tune.search.TPESearcher — the "
                "built-in TPE with the same algorithm and no dependency."
            ) from e
        super().__init__(metric=metric, mode=mode)
        from hyperopt import hp, tpe, Trials  # type: ignore
        self._hp, self._tpe, self._trials_cls = hp, tpe, Trials
        self._rng = random.Random(seed)
        self.num_samples = num_samples
        self._suggested = 0
        self._space = space
        self._trials = self._trials_cls()
        self._live: Dict[str, int] = {}

    def set_search_properties(self, metric, mode, space=None) -> bool:
        super().set_search_properties(metric, mode, space)
        if space and self._space is None:
            self._space = space
        return True

    def _hp_space(self):
        from ray_tpu.tune import sample as s
        dims, consts = flatten_space(self._space)
        out = {}
        for d in dims:
            # labels are repr(path): unambiguous even when a literal
            # dotted key ("a.b") aliases a nested path ("a"->"b")
            label = repr(d.path)
            dom = d.domain
            if isinstance(dom, s.Categorical):
                out[label] = self._hp.choice(label, dom.categories)
            elif isinstance(dom, s.LogUniform):
                import math
                out[label] = self._hp.loguniform(
                    label, math.log(dom.lower), math.log(dom.upper))
            elif isinstance(dom, s.Randint):
                out[label] = self._hp.randint(label, dom.lower, dom.upper)
            elif isinstance(dom, s.QUniform):
                out[label] = self._hp.quniform(
                    label, dom.lower, dom.upper, dom.q)
            elif isinstance(dom, s.Normal):
                out[label] = self._hp.normal(label, dom.mean, dom.sd)
            else:
                out[label] = self._hp.uniform(label, dom.lower, dom.upper)
        return out, consts

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._space is None:
            raise RuntimeError("HyperOptSearch needs a space")
        if self.num_samples is not None and \
                self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        import hyperopt
        hp_space, consts = self._hp_space()
        new_ids = self._trials.new_trial_ids(1)
        self._trials.refresh()
        docs = self._tpe.suggest(
            new_ids, hyperopt.base.Domain(lambda c: 0.0, hp_space),
            self._trials, self._rng.randrange(1 << 31))
        self._trials.insert_trial_docs(docs)
        self._trials.refresh()
        vals = {k: v[0] for k, v in docs[0]["misc"]["vals"].items() if v}
        self._live[trial_id] = new_ids[0]
        from ray_tpu.tune import sample as s
        dims, _ = flatten_space(self._space)
        by_label = {repr(d.path): d for d in dims}
        flat = dict(consts)
        for label, v in vals.items():
            dim = by_label[label]
            dom = dim.domain
            if isinstance(dom, s.Categorical):
                # hp.choice stores the chosen INDEX, not the value
                v = dom.categories[int(v)]
            # key by the dimension's PATH, not a split of the label —
            # a space key containing a dot is one key, not a nest
            flat[dim.path] = v
        return resolve(unflatten(flat), self._rng)

    def on_trial_complete(self, trial_id, result=None, error=False):
        import hyperopt
        tid = self._live.pop(trial_id, None)
        if tid is None:
            return
        for t in self._trials.trials:
            if t["tid"] != tid:
                continue
            if error or not result or self.metric not in result:
                t["state"] = hyperopt.JOB_STATE_ERROR
            else:
                loss = float(result[self.metric])
                if self.mode == "max":
                    loss = -loss
                t["state"] = hyperopt.JOB_STATE_DONE
                t["result"] = {"loss": loss, "status": "ok"}
        self._trials.refresh()


class OptunaSearch(Searcher):
    """optuna-backed searcher (requires the `optuna` package)."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 num_samples: Optional[int] = None,
                 seed: Optional[int] = None):
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the `optuna` package, which is not "
                "installed. Use ray_tpu.tune.search.TPESearcher (TPE, "
                "optuna's default sampler) or BayesOptSearch instead."
            ) from e
        super().__init__(metric=metric, mode=mode)
        import optuna
        self._optuna = optuna
        self._rng = random.Random(seed)
        self.num_samples = num_samples
        self._suggested = 0
        self._space = space
        self._study = optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=optuna.samplers.TPESampler(seed=seed))
        self._live: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, space=None) -> bool:
        super().set_search_properties(metric, mode, space)
        if space and self._space is None:
            self._space = space
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._space is None:
            raise RuntimeError("OptunaSearch needs a space")
        if self.num_samples is not None and \
                self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        from ray_tpu.tune import sample as s
        ot = self._study.ask()
        dims, consts = flatten_space(self._space)
        flat = dict(consts)
        for d in dims:
            label = ".".join(d.path)
            dom = d.domain
            if isinstance(dom, s.Categorical):
                flat[d.path] = ot.suggest_categorical(label, dom.categories)
            elif isinstance(dom, s.LogUniform):
                flat[d.path] = ot.suggest_float(
                    label, dom.lower, dom.upper, log=True)
            elif isinstance(dom, s.Randint):
                flat[d.path] = ot.suggest_int(label, dom.lower,
                                              dom.upper - 1)
            elif isinstance(dom, s.QUniform):
                flat[d.path] = ot.suggest_float(
                    label, dom.lower, dom.upper, step=dom.q)
            else:
                flat[d.path] = ot.suggest_float(label, dom.lower, dom.upper)
        self._live[trial_id] = ot
        return resolve(unflatten(flat), self._rng)

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._live.pop(trial_id, None)
        if ot is None:
            return
        if error or not result or self.metric not in result:
            self._study.tell(ot, state=self._optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot, float(result[self.metric]))


# --------------------------------------------------------------------------
# Generic ask/tell bridge for the remaining external libraries
# (reference: tune/search/{ax,skopt,nevergrad,flaml,zoopt,dragonfly,
# sigopt,hebo} — every one soft-imports its backing package).  The four
# with stable ask/tell APIs get full adapters; the rest gate with a
# pointer at the built-in equivalents.  All of them are exercised in
# tests through interface mocks of the backing package (SURVEY §4's
# mock strategy), since none of these libraries ship in this image.
# --------------------------------------------------------------------------


def _num_bounds(dim):
    """A Dimension's bounds in VALUE space (log dims store them in
    log-base space)."""
    if dim.log:
        return dim.base ** dim.lo, dim.base ** dim.hi
    return dim.lo, dim.hi


class _AskTellSearch(Searcher):
    """Shared skeleton: translate the space once, ask per suggest, tell
    per completion (sign-flipped to the library's minimize convention
    when needed).  Function (sample_from) dimensions are never handed
    to the library — their Domain rides through to resolve(), which
    samples it after the modeled values are in place.  Quantized /
    integer dimensions are rounded on the way back."""

    _package = ""          # import name
    _hint = ""             # native alternative

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 num_samples: Optional[int] = None,
                 seed: Optional[int] = None, **lib_kwargs):
        try:
            __import__(self._package)
        except ImportError as e:
            raise ImportError(
                f"{type(self).__name__} requires the `{self._package}` "
                f"package, which is not installed. {self._hint}") from e
        super().__init__(metric=metric, mode=mode)
        self._rng = random.Random(seed)
        self.num_samples = num_samples
        self._suggested = 0
        self._space = space
        self._seed = seed
        self._lib_kwargs = lib_kwargs
        self._live: Dict[str, Any] = {}
        self._impl = None

    def set_search_properties(self, metric, mode, space=None) -> bool:
        super().set_search_properties(metric, mode, space)
        if space and self._space is None:
            self._space = space
        return True

    # subclass hooks ------------------------------------------------------
    def _setup(self):
        """Build self._impl from self._ext_dims."""
        raise NotImplementedError

    def _ask(self):
        """-> (handle, {Dimension: raw_value}) over self._ext_dims, or
        None when the library wants the caller to back off."""
        raise NotImplementedError

    def _tell(self, handle, loss: float, error: bool):
        raise NotImplementedError

    # ---------------------------------------------------------------------

    def _prepare(self):
        dims, consts = flatten_space(self._space)
        self._consts = consts
        self._ext_dims = [d for d in dims if d.kind != "func"]
        self._func_dims = [d for d in dims if d.kind == "func"]
        self._setup()

    @staticmethod
    def _post(dim, v):
        """Round a numeric suggestion to the dimension's grid."""
        if dim.kind == "num":
            if dim.quant:
                v = round(v / dim.quant) * dim.quant
            if dim.integer:
                v = int(round(v))
        return v

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._space is None:
            raise RuntimeError(f"{type(self).__name__} needs a space")
        if self.num_samples is not None and \
                self._suggested >= self.num_samples:
            return None
        if self._impl is None:
            self._prepare()
        asked = self._ask()
        if asked is None:
            return None  # library backoff: no budget consumed
        self._suggested += 1
        handle, values = asked
        merged = dict(self._consts)
        for d, v in values.items():
            merged[d.path] = self._post(d, v)
        for d in self._func_dims:
            merged[d.path] = d.domain  # resolve() samples it below
        self._live[trial_id] = handle
        return resolve(unflatten(merged), self._rng)

    def on_trial_complete(self, trial_id, result=None, error=False):
        handle = self._live.pop(trial_id, None)
        if handle is None:
            return
        if error or not result or self.metric not in result:
            self._tell(handle, float("inf"), True)
            return
        loss = float(result[self.metric])
        if self.mode == "max":
            loss = -loss   # libraries minimize
        self._tell(handle, loss, False)


class SkOptSearch(_AskTellSearch):
    """scikit-optimize `Optimizer.ask/tell` (GP/forest surrogates)."""

    _package = "skopt"
    _hint = ("Use ray_tpu.tune.search.BayesOptSearch — the built-in "
             "GP-based Bayesian optimizer with no dependency.")

    def _setup(self):
        import skopt
        sk_dims = []
        for d in self._ext_dims:
            label = ".".join(d.path)
            if d.kind == "cat":
                sk_dims.append(skopt.space.Categorical(
                    list(d.categories), name=label))
            elif d.integer:
                lo, hi = _num_bounds(d)
                sk_dims.append(skopt.space.Integer(
                    int(lo), int(hi), name=label))
            else:
                lo, hi = _num_bounds(d)
                sk_dims.append(skopt.space.Real(
                    lo, hi, prior="log-uniform" if d.log else "uniform",
                    name=label))
        self._impl = skopt.Optimizer(
            sk_dims, random_state=self._seed, **self._lib_kwargs)
        self._worst_loss = None
        self._best_loss = None
        self._pending_errors = []

    def _ask(self):
        x = self._impl.ask()
        return list(x), dict(zip(self._ext_dims, x))

    def _tell(self, handle, loss, error):
        if error:
            # skopt has no failure state; tell it a penalized objective so
            # the optimizer learns the region is bad instead of re-suggesting
            # configurations near the failing point.  Until a real loss has
            # been observed there is no scale to penalize against — park the
            # handle and flush it after the first success.
            if self._worst_loss is None:
                self._pending_errors.append(handle)
            else:
                self._impl.tell(handle, self._penalty())
            return
        self._worst_loss = loss if self._worst_loss is None \
            else max(self._worst_loss, loss)
        self._best_loss = loss if self._best_loss is None \
            else min(self._best_loss, loss)
        self._impl.tell(handle, loss)
        while self._pending_errors:
            self._impl.tell(self._pending_errors.pop(), self._penalty())

    def _penalty(self):
        # Strictly worse than everything observed, by the observed range
        # (or a fixed margin when the range is degenerate), so a failed
        # config never looks comparatively good as new results arrive.
        span = self._worst_loss - self._best_loss
        margin = span if span > 0 else abs(self._worst_loss) * 0.1 + 1.0
        return self._worst_loss + margin


class NevergradSearch(_AskTellSearch):
    """nevergrad ask/tell over a parametrization Dict."""

    _package = "nevergrad"
    _hint = ("Use ray_tpu.tune.search.TPESearcher or BayesOptSearch — "
             "built-in derivative-free optimizers with no dependency.")

    def __init__(self, *args, optimizer: str = "NGOpt", budget: int = 100,
                 **kw):
        self._optimizer_name = optimizer
        self._budget = budget
        super().__init__(*args, **kw)

    def _setup(self):
        import nevergrad as ng
        params = {}
        self._by_label = {}
        for d in self._ext_dims:
            label = ".".join(d.path)
            self._by_label[label] = d
            if d.kind == "cat":
                params[label] = ng.p.Choice(list(d.categories))
            elif d.log:
                lo, hi = _num_bounds(d)
                params[label] = ng.p.Log(lower=lo, upper=hi)
            elif d.integer:
                params[label] = ng.p.Scalar(
                    lower=d.lo, upper=d.hi).set_integer_casting()
            else:
                params[label] = ng.p.Scalar(lower=d.lo, upper=d.hi)
        opt_cls = ng.optimizers.registry[self._optimizer_name]
        parametrization = ng.p.Dict(**params)
        if self._seed is not None:
            parametrization.random_state.seed(self._seed)
        self._impl = opt_cls(parametrization=parametrization,
                             budget=self._budget)

    def _ask(self):
        cand = self._impl.ask()
        return cand, {self._by_label[label]: v
                      for label, v in cand.value.items()}

    def _tell(self, handle, loss, error):
        if error:
            return  # an inf loss poisons CMA/ES covariance updates
        self._impl.tell(handle, loss)


class AxSearch(_AskTellSearch):
    """Ax (Adaptive Experimentation) via AxClient trials."""

    _package = "ax"
    _hint = ("Use ray_tpu.tune.search.BayesOptSearch — the built-in "
             "GP-based Bayesian optimizer with no dependency.")

    def _setup(self):
        from ax.service.ax_client import AxClient
        params = []
        self._by_label = {}
        for d in self._ext_dims:
            label = ".".join(d.path)
            self._by_label[label] = d
            if d.kind == "cat":
                params.append({"name": label, "type": "choice",
                               "values": list(d.categories)})
            elif d.integer:
                lo, hi = _num_bounds(d)
                params.append({"name": label, "type": "range",
                               "bounds": [int(lo), int(hi)],
                               "value_type": "int"})
            else:
                lo, hi = _num_bounds(d)
                params.append({"name": label, "type": "range",
                               "bounds": [lo, hi], "log_scale": d.log})
        self._impl = AxClient(random_seed=self._seed,
                              verbose_logging=False)
        self._impl.create_experiment(
            name="ray_tpu_tune", parameters=params,
            objective_name=self.metric or "objective",
            minimize=True, **self._lib_kwargs)

    def _ask(self):
        values, idx = self._impl.get_next_trial()
        return idx, {self._by_label[label]: v
                     for label, v in values.items()}

    def _tell(self, handle, loss, error):
        if error:
            self._impl.log_trial_failure(handle)
            return
        self._impl.complete_trial(
            handle, raw_data={(self.metric or "objective"): loss})


class FLAMLSearch(_AskTellSearch):
    """flaml BlendSearch/CFO (they speak tune-style Searcher natively)."""

    _package = "flaml"
    _hint = ("Use ray_tpu.tune.search.TPESearcher with ASHA scheduling — "
             "the built-in cost-aware combination.")

    def __init__(self, *args, searcher: str = "BlendSearch", **kw):
        self._searcher_name = searcher
        self._asked = 0
        super().__init__(*args, **kw)

    def _setup(self):
        import flaml
        from flaml import tune as ftune
        # flaml consumes tune-style sample objects, same API shape as
        # this framework's ray_tpu.tune.sample
        space = {}
        self._by_label = {}
        for d in self._ext_dims:
            label = ".".join(d.path)
            self._by_label[label] = d
            if d.kind == "cat":
                space[label] = ftune.choice(list(d.categories))
            elif d.log:
                lo, hi = _num_bounds(d)
                space[label] = ftune.loguniform(lo, hi)
            elif d.integer:
                space[label] = ftune.randint(int(d.lo), int(d.hi) + 1)
            else:
                space[label] = ftune.uniform(d.lo, d.hi)
        cls = getattr(flaml, self._searcher_name)
        self._impl = cls(metric=self.metric,
                         mode="min",  # losses are sign-normalized here
                         space=space, **self._lib_kwargs)

    def _ask(self):
        tid = f"flaml_{self._asked}"
        cfg = self._impl.suggest(tid)
        if cfg is None:
            return None  # flaml backoff: all points in flight
        self._asked += 1
        return tid, {self._by_label[label]: v for label, v in cfg.items()
                     if label in self._by_label}

    def _tell(self, handle, loss, error):
        self._impl.on_trial_complete(
            handle, result=None if error else {self.metric: loss},
            error=error)


def _gated_only(name: str, package: str, hint: str):
    """Searcher classes for libraries with no stable offline-testable
    ask/tell surface: constructing without the package raises the same
    guidance the full adapters give (reference behavior for missing
    integrations)."""

    def __init__(self, *a, **kw):
        try:
            __import__(package)
        except ImportError as e:
            raise ImportError(
                f"{name} requires the `{package}` package, which is not "
                f"installed. {hint}") from e
        raise NotImplementedError(
            f"{name}: `{package}` is present but this adapter only "
            f"gates; contribute the binding or use the built-in "
            f"equivalent. {hint}")

    return type(name, (Searcher,), {"__init__": __init__})


ZOOptSearch = _gated_only(
    "ZOOptSearch", "zoopt",
    "Use ray_tpu.tune.search.TPESearcher (sequential model-based "
    "derivative-free search).")
DragonflySearch = _gated_only(
    "DragonflySearch", "dragonfly",
    "Use ray_tpu.tune.search.BayesOptSearch (GP-based Bayesian "
    "optimization).")
SigOptSearch = _gated_only(
    "SigOptSearch", "sigopt",
    "SigOpt is a hosted service; use ray_tpu.tune.search.BayesOptSearch "
    "locally.")
HEBOSearch = _gated_only(
    "HEBOSearch", "hebo",
    "Use ray_tpu.tune.search.BayesOptSearch (GP-based Bayesian "
    "optimization).")
