"""Soft-gated adapters for external optimization libraries.

Reference: tune/search/hyperopt/hyperopt_search.py,
search/optuna/optuna_search.py — both soft-import their backing library.
Neither ships in this image; when absent these adapters raise an
ImportError pointing at the native equivalents (TPESearcher /
BayesOptSearch), which cover the same capability without the dependency.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

from ray_tpu.tune.sample import resolve
from ray_tpu.tune.search._space import flatten_space, unflatten
from ray_tpu.tune.search.searcher import Searcher


class HyperOptSearch(Searcher):
    """hyperopt-backed TPE (requires the `hyperopt` package)."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 num_samples: Optional[int] = None,
                 seed: Optional[int] = None):
        try:
            import hyperopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "HyperOptSearch requires the `hyperopt` package, which is "
                "not installed. Use ray_tpu.tune.search.TPESearcher — the "
                "built-in TPE with the same algorithm and no dependency."
            ) from e
        super().__init__(metric=metric, mode=mode)
        from hyperopt import hp, tpe, Trials  # type: ignore
        self._hp, self._tpe, self._trials_cls = hp, tpe, Trials
        self._rng = random.Random(seed)
        self.num_samples = num_samples
        self._suggested = 0
        self._space = space
        self._trials = self._trials_cls()
        self._live: Dict[str, int] = {}

    def set_search_properties(self, metric, mode, space=None) -> bool:
        super().set_search_properties(metric, mode, space)
        if space and self._space is None:
            self._space = space
        return True

    def _hp_space(self):
        from ray_tpu.tune import sample as s
        dims, consts = flatten_space(self._space)
        out = {}
        for d in dims:
            label = ".".join(d.path)
            dom = d.domain
            if isinstance(dom, s.Categorical):
                out[label] = self._hp.choice(label, dom.categories)
            elif isinstance(dom, s.LogUniform):
                import math
                out[label] = self._hp.loguniform(
                    label, math.log(dom.lower), math.log(dom.upper))
            elif isinstance(dom, s.Randint):
                out[label] = self._hp.randint(label, dom.lower, dom.upper)
            elif isinstance(dom, s.QUniform):
                out[label] = self._hp.quniform(
                    label, dom.lower, dom.upper, dom.q)
            elif isinstance(dom, s.Normal):
                out[label] = self._hp.normal(label, dom.mean, dom.sd)
            else:
                out[label] = self._hp.uniform(label, dom.lower, dom.upper)
        return out, consts

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._space is None:
            raise RuntimeError("HyperOptSearch needs a space")
        if self.num_samples is not None and \
                self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        import hyperopt
        hp_space, consts = self._hp_space()
        new_ids = self._trials.new_trial_ids(1)
        self._trials.refresh()
        docs = self._tpe.suggest(
            new_ids, hyperopt.base.Domain(lambda c: 0.0, hp_space),
            self._trials, self._rng.randrange(1 << 31))
        self._trials.insert_trial_docs(docs)
        self._trials.refresh()
        vals = {k: v[0] for k, v in docs[0]["misc"]["vals"].items() if v}
        self._live[trial_id] = new_ids[0]
        from ray_tpu.tune import sample as s
        dims, _ = flatten_space(self._space)
        by_label = {".".join(d.path): d for d in dims}
        flat = dict(consts)
        for label, v in vals.items():
            dom = by_label[label].domain
            if isinstance(dom, s.Categorical):
                # hp.choice stores the chosen INDEX, not the value
                v = dom.categories[int(v)]
            flat[tuple(label.split("."))] = v
        return resolve(unflatten(flat), self._rng)

    def on_trial_complete(self, trial_id, result=None, error=False):
        import hyperopt
        tid = self._live.pop(trial_id, None)
        if tid is None:
            return
        for t in self._trials.trials:
            if t["tid"] != tid:
                continue
            if error or not result or self.metric not in result:
                t["state"] = hyperopt.JOB_STATE_ERROR
            else:
                loss = float(result[self.metric])
                if self.mode == "max":
                    loss = -loss
                t["state"] = hyperopt.JOB_STATE_DONE
                t["result"] = {"loss": loss, "status": "ok"}
        self._trials.refresh()


class OptunaSearch(Searcher):
    """optuna-backed searcher (requires the `optuna` package)."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 num_samples: Optional[int] = None,
                 seed: Optional[int] = None):
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the `optuna` package, which is not "
                "installed. Use ray_tpu.tune.search.TPESearcher (TPE, "
                "optuna's default sampler) or BayesOptSearch instead."
            ) from e
        super().__init__(metric=metric, mode=mode)
        import optuna
        self._optuna = optuna
        self._rng = random.Random(seed)
        self.num_samples = num_samples
        self._suggested = 0
        self._space = space
        self._study = optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=optuna.samplers.TPESampler(seed=seed))
        self._live: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, space=None) -> bool:
        super().set_search_properties(metric, mode, space)
        if space and self._space is None:
            self._space = space
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._space is None:
            raise RuntimeError("OptunaSearch needs a space")
        if self.num_samples is not None and \
                self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        from ray_tpu.tune import sample as s
        ot = self._study.ask()
        dims, consts = flatten_space(self._space)
        flat = dict(consts)
        for d in dims:
            label = ".".join(d.path)
            dom = d.domain
            if isinstance(dom, s.Categorical):
                flat[d.path] = ot.suggest_categorical(label, dom.categories)
            elif isinstance(dom, s.LogUniform):
                flat[d.path] = ot.suggest_float(
                    label, dom.lower, dom.upper, log=True)
            elif isinstance(dom, s.Randint):
                flat[d.path] = ot.suggest_int(label, dom.lower,
                                              dom.upper - 1)
            elif isinstance(dom, s.QUniform):
                flat[d.path] = ot.suggest_float(
                    label, dom.lower, dom.upper, step=dom.q)
            else:
                flat[d.path] = ot.suggest_float(label, dom.lower, dom.upper)
        self._live[trial_id] = ot
        return resolve(unflatten(flat), self._rng)

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._live.pop(trial_id, None)
        if ot is None:
            return
        if error or not result or self.metric not in result:
            self._study.tell(ot, state=self._optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot, float(result[self.metric]))
