"""BOHB — Bayesian Optimization + HyperBand (Falkner et al. 2018).

Reference analogue: tune/search/bohb/bohb_search.py (TuneBOHB wrapping
hpbandster's KDE model) + tune/schedulers/hb_bohb.py (HyperBandForBOHB).
Neither hpbandster nor ConfigSpace ships in this image, so the model
component is implemented natively on top of the in-repo TPE machinery:
BOHB's model IS a TPE-style Parzen estimator, fit per BUDGET — the
searcher conditions its kernel-density split on the observations at the
LARGEST budget that has enough of them, so early low-fidelity results
guide sampling until high-fidelity results take over.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.search.tpe import TPESearcher


class BOHBSearcher(TPESearcher):
    """TPE model conditioned on the largest sufficiently-observed
    budget (the BOHB rule, Falkner et al. §4: "the model of the
    highest budget with at least d+1 observations")."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 num_samples: Optional[int] = None,
                 time_attr: str = "training_iteration",
                 min_points_in_model: Optional[int] = None,
                 **kw):
        super().__init__(space, metric=metric, mode=mode,
                         num_samples=num_samples, **kw)
        self.time_attr = time_attr
        self._min_points = min_points_in_model
        # budget -> list of (flat values, score); a trial contributes its
        # LATEST observation per budget
        self._budget_obs: Dict[int, Dict[str, Tuple[List[Any], float]]] = {}

    def _min_pts(self) -> int:
        if self._min_points is not None:
            return self._min_points
        return max(3, len(self._dims) + 1)

    def _record(self, trial_id: str, budget: int, score: float):
        flat = self._live.get(trial_id)
        if flat is None:
            return
        row = [flat[d.path] for d in self._dims]
        self._budget_obs.setdefault(budget, {})[trial_id] = (row, score)

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        if self.metric in result and self.time_attr in result:
            score = result[self.metric]
            if self.mode == "min":
                score = -score
            self._record(trial_id, int(result[self.time_attr]), score)

    def on_trial_complete(self, trial_id: str, result=None, error=False):
        if result and self.metric in result:
            score = result[self.metric]
            if self.mode == "min":
                score = -score
            self._record(trial_id,
                         int(result.get(self.time_attr, 0)), score)
        self._live.pop(trial_id, None)

    def _suggest_flat(self) -> Dict[Tuple[str, ...], Any]:
        # BOHB rule: model the largest budget with enough observations
        need = self._min_pts()
        chosen: List[Tuple[List[Any], float]] = []
        for budget in sorted(self._budget_obs, reverse=True):
            obs = list(self._budget_obs[budget].values())
            if len(obs) >= need:
                chosen = obs
                break
        if not chosen:  # fall back to everything seen so far
            merged: Dict[str, Tuple[List[Any], float]] = {}
            for per_budget in self._budget_obs.values():
                merged.update(per_budget)
            chosen = list(merged.values())
        self._obs = chosen
        return super()._suggest_flat()
