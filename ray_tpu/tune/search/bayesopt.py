"""Native Gaussian-process Bayesian-optimization searcher.

Capability analogue of the reference's tune/search/bayesopt/bayesopt_search.py
(which wraps the `bayesian-optimization` package — not in this image, so the
GP is implemented here with numpy): RBF-kernel GP posterior on the warped
unit cube, expected-improvement acquisition maximized over a random
candidate sweep.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.sample import resolve
from ray_tpu.tune.search._space import (Dimension, flatten_space, unflatten)
from ray_tpu.tune.search.searcher import Searcher


class GP:
    """Minimal RBF-kernel GP with fixed hyperparameters on standardized y.

    Shared by BayesOptSearch and the PB2 scheduler (schedulers.py)."""

    def __init__(self, length_scale: float = 0.25, signal_var: float = 1.0,
                 noise_var: float = 1e-3):
        self.ls, self.sf2, self.sn2 = length_scale, signal_var, noise_var
        self._X: Optional[np.ndarray] = None

    def _k(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return self.sf2 * np.exp(-0.5 * d2 / (self.ls ** 2))

    def fit(self, X: np.ndarray, y: np.ndarray):
        self._X = np.asarray(X, dtype=np.float64)
        y = np.asarray(y, dtype=np.float64)
        self._ymu = float(y.mean())
        self._ysd = float(y.std()) or 1.0
        yn = (y - self._ymu) / self._ysd
        K = self._k(self._X, self._X)
        K[np.diag_indices_from(K)] += self.sn2
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, yn))

    def predict(self, Xs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean/std in the ORIGINAL y scale."""
        Ks = self._k(np.asarray(Xs, dtype=np.float64), self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.maximum(self.sf2 - (v ** 2).sum(0), 1e-12)
        return mu * self._ysd + self._ymu, np.sqrt(var) * self._ysd


def _norm_cdf(z: np.ndarray) -> np.ndarray:
    return 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))


def _norm_pdf(z: np.ndarray) -> np.ndarray:
    return np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)


def expected_improvement(mu: np.ndarray, sd: np.ndarray,
                         best: float, xi: float = 0.01) -> np.ndarray:
    imp = mu - best - xi
    z = imp / sd
    return imp * _norm_cdf(z) + sd * _norm_pdf(z)


class BayesOptSearch(Searcher):
    """GP-EI over the numeric dims; categorical/function dims are sampled
    from their prior each suggestion (the reference's bayesopt wrapper has
    the same numeric-only restriction)."""

    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 num_samples: Optional[int] = None,
                 n_startup_trials: int = 8, n_candidates: int = 256,
                 xi: float = 0.01, seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode)
        self._rng = random.Random(seed)
        self._np_rng = np.random.default_rng(seed)
        self.n_startup = n_startup_trials
        self.n_cand = n_candidates
        self.xi = xi
        self.num_samples = num_samples
        self._suggested = 0
        self._space: Optional[Dict[str, Any]] = None
        self._live: Dict[str, List[float]] = {}
        self._X: List[List[float]] = []
        self._y: List[float] = []
        if space is not None:
            self._set_space(space)

    def _set_space(self, space):
        self._space = space
        dims, self._consts = flatten_space(space)
        self._num_dims = [d for d in dims if d.kind == "num"]
        self._other_dims = [d for d in dims if d.kind != "num"]

    def set_search_properties(self, metric, mode, space=None) -> bool:
        super().set_search_properties(metric, mode, space)
        if space and self._space is None:
            self._set_space(space)
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._space is None:
            raise RuntimeError("BayesOptSearch needs a space")
        if self.num_samples is not None and \
                self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        d = len(self._num_dims)
        if d == 0 or len(self._y) < self.n_startup:
            units = [self._rng.random() for _ in range(d)]
        else:
            cand = self._np_rng.random((self.n_cand, d))
            # seed candidates near the incumbent too
            best_x = np.asarray(self._X[int(np.argmax(self._y))])
            near = np.clip(best_x + self._np_rng.normal(
                0, 0.05, (16, d)), 0, 1)
            cand = np.vstack([cand, near])
            gp = GP()
            gp.fit(np.asarray(self._X), np.asarray(self._y))
            mu, sd = gp.predict(cand)
            ei = expected_improvement(mu, sd, float(np.max(self._y)),
                                      self.xi)
            units = cand[int(np.argmax(ei))].tolist()
        self._live[trial_id] = units
        values = dict(self._consts)
        for dim, u in zip(self._num_dims, units):
            values[dim.path] = dim.from_unit(u)
        for dim in self._other_dims:
            values[dim.path] = dim.sample_native(self._rng)
        return resolve(unflatten(values), self._rng)

    def on_trial_complete(self, trial_id, result=None, error=False):
        units = self._live.pop(trial_id, None)
        if error or units is None or not result or \
                self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._X.append(units)
        self._y.append(score)
