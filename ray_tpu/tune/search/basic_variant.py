"""Default searchers: grid cross-product + random sampling.

Reference: tune/search/basic_variant.py (BasicVariantGenerator is the
default when no search_alg is given).
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.sample import expand_grid, resolve
from ray_tpu.tune.search.searcher import Searcher


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples random repeats (the default
    searcher; reference search/basic_variant.py)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._rng = random.Random(seed)
        self._variants: List[Dict[str, Any]] = []
        for _ in range(num_samples):
            self._variants.extend(expand_grid(space))
        self._next = 0

    @property
    def total_trials(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._next >= len(self._variants):
            return None
        variant = self._variants[self._next]
        self._next += 1
        return resolve(variant, self._rng)


class RandomSearch(Searcher):
    """Pure random sampling of a Domain-only space (no grid axes)."""

    def __init__(self, space: Dict[str, Any], num_samples: int,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._space = space
        self._remaining = num_samples
        self._rng = random.Random(seed)

    def suggest(self, trial_id):
        if self._remaining <= 0:
            return None
        self._remaining -= 1
        return resolve(self._space, self._rng)
