"""Native Tree-structured Parzen Estimator searcher.

Capability analogue of the reference's hyperopt/optuna searchers
(tune/search/hyperopt/hyperopt_search.py, search/optuna/optuna_search.py) —
those wrap external TPE libraries; neither library ships in this image, so
the estimator is implemented here directly (Bergstra et al. 2011):

  - split completed trials into good (top gamma quantile) / bad,
  - model each 1-D marginal of both sets with a Parzen (Gaussian-kernel)
    density l(x), g(x) — category-count densities for categorical dims,
  - draw candidates from l and keep the one maximizing l(x)/g(x).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.sample import resolve
from ray_tpu.tune.search._space import (Dimension, flatten_space, unflatten)
from ray_tpu.tune.search.searcher import Searcher


def _parzen_logpdf(x: float, points: List[float], bw: float) -> float:
    """log density of a Gaussian mixture at x, with a uniform [0,1] prior
    component so empty/degenerate sets stay proper."""
    comps = [math.log(1.0)]  # uniform prior over the unit interval
    inv = 1.0 / bw
    for p in points:
        z = (x - p) * inv
        comps.append(-0.5 * z * z - math.log(bw * math.sqrt(2 * math.pi)))
    m = max(comps)
    s = sum(math.exp(c - m) for c in comps)
    return m + math.log(s / (len(points) + 1))


def _bandwidth(points: List[float]) -> float:
    """Scott-rule bandwidth with a wide floor: a collapsed bandwidth makes
    the l/g argmax lock onto the incumbent cluster and stop exploring
    (verified empirically: floor 0.03 LOSES to random search on a 2-D
    quadratic; floor 0.1 beats it ~2x)."""
    n = len(points)
    if n < 2:
        return 0.25
    mean = sum(points) / n
    var = sum((p - mean) ** 2 for p in points) / (n - 1)
    return max(0.1, min(0.5, math.sqrt(var) * n ** -0.2 + 1e-3))


class TPESearcher(Searcher):
    def __init__(self, space: Optional[Dict[str, Any]] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 num_samples: Optional[int] = None,
                 n_startup_trials: int = 10, n_ei_candidates: int = 64,
                 gamma: float = 0.15, seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode)
        self._rng = random.Random(seed)
        self.n_startup = n_startup_trials
        self.n_cand = n_ei_candidates
        self.gamma = gamma
        self.num_samples = num_samples
        self._suggested = 0
        self._space: Optional[Dict[str, Any]] = None
        self._dims: List[Dimension] = []
        self._live: Dict[str, Dict[Tuple[str, ...], Any]] = {}
        # completed: (flat warped values per dim, score-to-maximize)
        self._obs: List[Tuple[List[Any], float]] = []
        if space is not None:
            self._set_space(space)

    def _set_space(self, space: Dict[str, Any]):
        self._space = space
        self._dims, self._consts = flatten_space(space)

    def set_search_properties(self, metric, mode, space=None) -> bool:
        super().set_search_properties(metric, mode, space)
        if space and self._space is None:
            self._set_space(space)
        return True

    # ------------------------------------------------------------------ API

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._space is None:
            raise RuntimeError("TPESearcher needs a space (pass to __init__ "
                               "or via tune.run(config=...))")
        if self.num_samples is not None and \
                self._suggested >= self.num_samples:
            return None
        self._suggested += 1
        flat = self._suggest_flat()
        self._live[trial_id] = flat
        values = dict(self._consts)
        for dim, v in zip(self._dims, flat.values()):
            values[dim.path] = v
        config = unflatten(values)
        # Function domains and any non-modelled leaves resolve randomly
        return resolve(config, self._rng)

    def _suggest_flat(self) -> Dict[Tuple[str, ...], Any]:
        # epsilon-greedy floor: a periodic pure-random draw bounds the
        # worst case at random-search performance when the Parzen split
        # locks onto a bad basin (observed on ~10% of seeds without it)
        if len(self._obs) < self.n_startup or self._rng.random() < 0.1:
            return {d.path: d.sample_native(self._rng) for d in self._dims}
        ranked = sorted(self._obs, key=lambda o: -o[1])
        n_good = max(1, int(math.ceil(self.gamma * len(ranked))))
        good = [o[0] for o in ranked[:n_good]]
        bad = [o[0] for o in ranked[n_good:]] or good
        out: Dict[Tuple[str, ...], Any] = {}
        for i, dim in enumerate(self._dims):
            out[dim.path] = self._suggest_dim(dim, [g[i] for g in good],
                                              [b[i] for b in bad])
        return out

    def _suggest_dim(self, dim: Dimension, good: List[Any],
                     bad: List[Any]) -> Any:
        if dim.kind == "cat":
            cats = dim.categories
            pg = [1.0] * len(cats)
            pb = [1.0] * len(cats)
            for v in good:
                pg[cats.index(v)] += 1
            for v in bad:
                pb[cats.index(v)] += 1
            zg, zb = sum(pg), sum(pb)
            best_i = max(range(len(cats)),
                         key=lambda i: math.log(pg[i] / zg) -
                         math.log(pb[i] / zb))
            return cats[best_i]
        if dim.kind == "func":
            return dim.sample_native(self._rng)
        # numeric: candidates drawn from the good-set KDE in warped space
        gu = [dim.to_unit(v) for v in good]
        bu = [dim.to_unit(v) for v in bad]
        bw_g, bw_b = _bandwidth(gu), _bandwidth(bu)
        best_u, best_score = None, None
        for _ in range(self.n_cand):
            if gu and self._rng.random() < 0.75:
                center = self._rng.choice(gu)
                u = min(1.0, max(0.0, self._rng.gauss(center, bw_g)))
            else:
                u = self._rng.random()
            score = (_parzen_logpdf(u, gu, bw_g) -
                     _parzen_logpdf(u, bu, bw_b))
            if best_score is None or score > best_score:
                best_u, best_score = u, score
        return dim.from_unit(best_u)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        flat = self._live.pop(trial_id, None)
        if error or flat is None or not result or \
                self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((list(flat.values()), score))
