"""Searcher ABC + ConcurrencyLimiter.

Reference: tune/search/searcher.py (ABC), search/concurrency_limiter.py.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# Sentinel: searcher not ready to suggest yet (at capacity) — distinct from
# None, which means the search space is exhausted.
PENDING = object()


class Searcher:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode

    def set_search_properties(self, metric, mode, space) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config, or None when exhausted."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        pass


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference: search/concurrency_limiter)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(metric=searcher.metric, mode=searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return PENDING  # runner retries later
        config = self.searcher.suggest(trial_id)
        if config is not None:
            self._live.add(trial_id)
        return config

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
