"""ray_tpu.tune.search — config suggestion strategies.

Reference: python/ray/tune/search/ — basic_variant.py (default),
searcher.py (ABC), concurrency_limiter.py, and the model-based searchers
(hyperopt/optuna/bayesopt wrappers). The model-based searchers here are
native implementations (tpe.py, bayesopt.py) since the external libraries
aren't in this image; gated adapters live in external.py.
"""

from ray_tpu.tune.search.searcher import (  # noqa: F401
    PENDING, ConcurrencyLimiter, Searcher)
from ray_tpu.tune.search.basic_variant import (  # noqa: F401
    BasicVariantGenerator, RandomSearch)
from ray_tpu.tune.search.bohb import BOHBSearcher  # noqa: F401
from ray_tpu.tune.search.tpe import TPESearcher  # noqa: F401
from ray_tpu.tune.search.bayesopt import BayesOptSearch  # noqa: F401
from ray_tpu.tune.search.external import (  # noqa: F401
    AxSearch, DragonflySearch, FLAMLSearch, HEBOSearch, HyperOptSearch,
    NevergradSearch, OptunaSearch, SigOptSearch, SkOptSearch, ZOOptSearch)
