"""Flattened view of a nested search space, shared by the model-based
searchers (TPE, BayesOpt, PB2).

Each leaf Domain becomes a Dimension with a numeric warped range [0, 1]
(log-warped for LogUniform) or a category list; model-based searchers
operate on the warped unit cube and unwarp before handing configs back.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.sample import (Categorical, Domain, Function, GridSearch,
                                 LogUniform, Normal, QUniform, Randint,
                                 Uniform)

Path = Tuple[str, ...]


class Dimension:
    """One search dimension: warp/unwarp between native values and [0,1]."""

    def __init__(self, path: Path, domain: Domain):
        self.path = path
        self.domain = domain
        d = domain
        if isinstance(d, Categorical):
            self.kind = "cat"
            self.categories = d.categories
        elif isinstance(d, LogUniform):
            self.kind = "num"
            self.lo, self.hi = d._log  # already in log_base space
            self.base = d.base
            self.quant = None
            self.integer = False
            self.log = True
        elif isinstance(d, Uniform):
            self.kind = "num"
            self.lo, self.hi = d.lower, d.upper
            self.quant, self.integer, self.log = None, False, False
        elif isinstance(d, QUniform):
            self.kind = "num"
            self.lo, self.hi = d.lower, d.upper
            self.quant, self.integer, self.log = d.q, False, False
        elif isinstance(d, Randint):
            self.kind = "num"
            self.lo, self.hi = float(d.lower), float(d.upper - 1)
            self.quant, self.integer, self.log = 1.0, True, False
        elif isinstance(d, Normal):
            # treat as numeric over ±4σ for modeling purposes
            self.kind = "num"
            self.lo = d.mean - 4 * d.sd
            self.hi = d.mean + 4 * d.sd
            self.quant, self.integer, self.log = None, False, False
        elif isinstance(d, Function):
            self.kind = "func"
        else:
            raise TypeError(f"unsupported domain {type(d).__name__}")

    # -- numeric warping ---------------------------------------------------

    def to_unit(self, value: Any) -> float:
        """Native value → [0,1] (numeric dims only)."""
        v = float(value)
        if self.log:
            v = math.log(v, self.base)
        if self.hi == self.lo:
            return 0.0
        return min(1.0, max(0.0, (v - self.lo) / (self.hi - self.lo)))

    def from_unit(self, u: float) -> Any:
        u = min(1.0, max(0.0, u))
        v = self.lo + u * (self.hi - self.lo)
        if self.log:
            v = self.base ** v
        if self.quant is not None:
            v = round(v / self.quant) * self.quant
        if self.integer:
            v = int(round(v))
        return v

    def sample_native(self, rng: random.Random) -> Any:
        return self.domain.sample(rng)


def flatten_space(space: Dict[str, Any]) -> Tuple[List[Dimension],
                                                  Dict[Path, Any]]:
    """Split a nested space into model-able Dimensions + constant leaves."""
    dims: List[Dimension] = []
    consts: Dict[Path, Any] = {}

    def walk(d: Dict[str, Any], prefix: Path):
        for k, v in d.items():
            p = prefix + (k,)
            if isinstance(v, GridSearch):
                raise ValueError(
                    "grid_search is only supported by BasicVariantGenerator;"
                    f" found one at {'.'.join(p)}")
            if isinstance(v, Domain):
                dims.append(Dimension(p, v))
            elif isinstance(v, dict):
                walk(v, p)
            else:
                consts[p] = v

    walk(space, ())
    return dims, consts


def unflatten(values: Dict[Path, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for path, v in values.items():
        d = out
        for p in path[:-1]:
            d = d.setdefault(p, {})
        d[path[-1]] = v
    return out


