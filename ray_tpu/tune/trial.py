"""Trial state + per-trial checkpoint manager.

Reference: tune/experiment/trial.py (status machine) and
tune/execution/checkpoint_manager.py (top-K retention by metric,
CheckpointConfig air/config.py:513).
"""

from __future__ import annotations

import itertools
import uuid
from typing import Any, Dict, List, Optional, Tuple

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"

_trial_counter = itertools.count()


class CheckpointManager:
    """Keep the top-K checkpoints by score (None = keep all)."""

    def __init__(self, num_to_keep: Optional[int] = None,
                 metric: Optional[str] = None, mode: str = "max"):
        self.num_to_keep = num_to_keep
        self.metric, self.mode = metric, mode
        self._items: List[Tuple[float, int, Any]] = []  # (score, seq, ckpt)
        self._seq = 0

    def add(self, checkpoint, metrics: Dict[str, Any]):
        score = 0.0
        if self.metric and self.metric in metrics:
            score = float(metrics[self.metric])
            if self.mode == "min":
                score = -score
        self._items.append((score, self._seq, checkpoint))
        self._seq += 1
        if self.num_to_keep is not None and \
                len(self._items) > self.num_to_keep:
            # evict the lowest-scored; on score ties the oldest goes first
            worst = min(self._items, key=lambda t: (t[0], t[1]))
            self._items.remove(worst)

    @property
    def best(self):
        if not self._items:
            return None
        return max(self._items, key=lambda t: (t[0], t[1]))[2]

    @property
    def latest(self):
        if not self._items:
            return None
        return max(self._items, key=lambda t: t[1])[2]

    @property
    def checkpoints(self) -> List[Any]:
        return [c for _, _, c in sorted(self._items, key=lambda t: t[1])]


class Trial:
    def __init__(self, config: Dict[str, Any],
                 experiment_name: str = "exp",
                 resources: Optional[Dict[str, float]] = None,
                 num_to_keep: Optional[int] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 max_failures: int = 0):
        self.index = next(_trial_counter)
        self.trial_id = f"{uuid.uuid4().hex[:8]}_{self.index}"
        self.trial_name = f"{experiment_name}_{self.index:05d}"
        self.config = config
        self.resources = dict(resources or {"CPU": 1.0})
        self.status = PENDING
        self.results: List[Dict[str, Any]] = []
        self.error: Optional[str] = None
        self.num_failures = 0
        self.max_failures = max_failures
        self.ckpt_manager = CheckpointManager(num_to_keep, metric, mode)
        self.logdir: Optional[str] = None  # set by the runner
        # runner-owned handles
        self.actor = None
        self.future = None

    def __getstate__(self):
        """Snapshot for experiment_state.pkl: drop live handles."""
        state = self.__dict__.copy()
        state["actor"] = None
        state["future"] = None
        return state

    @property
    def last_result(self) -> Optional[Dict[str, Any]]:
        return self.results[-1] if self.results else None

    @property
    def latest_checkpoint(self):
        return self.ckpt_manager.latest

    @property
    def best_checkpoint(self):
        return self.ckpt_manager.best

    def metric_history(self, metric: str) -> List[float]:
        return [float(r[metric]) for r in self.results if metric in r]

    def __repr__(self):
        return (f"Trial({self.trial_name}, {self.status}, "
                f"iters={len(self.results)})")
