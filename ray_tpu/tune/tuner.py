"""Tuner: the modern entry point (reference: tune/tuner.py:44, fit:249)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Union

from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import Searcher
from ray_tpu.tune.tune import ExperimentAnalysis, run
from ray_tpu.tune.trial import Trial


@dataclasses.dataclass
class TuneConfig:
    """Reference: tune/tune_config.py."""
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    max_concurrent_trials: int = 4


class Result:
    """One trial's outcome (reference: air/result.py)."""

    def __init__(self, trial: Trial):
        self.metrics = trial.last_result or {}
        self.checkpoint = trial.best_checkpoint
        self.config = trial.config
        self.error = trial.error
        self.trial = trial

    @property
    def best_checkpoints(self):
        return trial_checkpoints(self.trial)


def trial_checkpoints(trial: Trial):
    return [(c, None) for c in trial.ckpt_manager.checkpoints]


class ResultGrid:
    """Reference: tune/result_grid.py."""

    def __init__(self, analysis: ExperimentAnalysis):
        self._analysis = analysis
        self._results = [Result(t) for t in analysis.trials]

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        t = self._analysis.get_best_trial(metric, mode)
        if t is None:
            raise RuntimeError("no trial produced the requested metric")
        return Result(t)

    def get_dataframe(self):
        return self._analysis.dataframe()

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]


class Tuner:
    def __init__(self, trainable: Union[Callable, type],
                 *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config=None,
                 _resume: bool = False):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config
        self._resume = _resume

    @classmethod
    def restore(cls, path: str, trainable: Union[Callable, type],
                *, param_space: Optional[Dict[str, Any]] = None,
                tune_config: Optional[TuneConfig] = None,
                run_config=None) -> "Tuner":
        """Resume an interrupted experiment from its experiment dir
        (reference: tune/tuner.py Tuner.restore:149). `path` is
        <local_dir>/<name>; finished trials are kept, unfinished ones
        restart from their latest checkpoint. The original run's
        checkpoint/failure/stop settings are restored from the experiment
        snapshot; pass run_config to supply the non-persisted pieces
        (callbacks, sync_config)."""
        import os
        from ray_tpu.air.config import RunConfig
        path = os.path.expanduser(path.rstrip("/"))
        if run_config is None:
            run_config = RunConfig()
        run_config.name = os.path.basename(path)
        run_config.storage_path = os.path.dirname(path)
        return cls(trainable, param_space=param_space,
                   tune_config=tune_config, run_config=run_config,
                   _resume=True)

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        rc = self._run_config
        stop = None
        name = "exp"
        checkpoint_freq = 0
        num_to_keep = None
        max_failures = 0
        local_dir = None
        callbacks = None
        sync_config = None
        if rc is not None:
            stop = getattr(rc, "stop", None)
            name = getattr(rc, "name", None) or "exp"
            local_dir = getattr(rc, "storage_path", None)
            callbacks = getattr(rc, "callbacks", None)
            sync_config = getattr(rc, "sync_config", None)
            ckpt_cfg = getattr(rc, "checkpoint_config", None)
            if ckpt_cfg is not None:
                checkpoint_freq = getattr(
                    ckpt_cfg, "checkpoint_frequency", 0)
                num_to_keep = getattr(ckpt_cfg, "num_to_keep", None)
            fail_cfg = getattr(rc, "failure_config", None)
            if fail_cfg is not None:
                max_failures = getattr(fail_cfg, "max_failures", 0)
        analysis = run(
            self._trainable,
            config=self._param_space,
            num_samples=tc.num_samples,
            metric=tc.metric, mode=tc.mode,
            search_alg=tc.search_alg, scheduler=tc.scheduler,
            max_concurrent_trials=tc.max_concurrent_trials,
            stop=stop, name=name,
            checkpoint_freq=checkpoint_freq,
            keep_checkpoints_num=num_to_keep,
            max_failures=max_failures,
            local_dir=local_dir, callbacks=callbacks,
            sync_config=sync_config, resume=self._resume)
        return ResultGrid(analysis)
