"""TrialRunner event loop + tune.run().

Reference call stack (SURVEY.md §3.4): Tuner.fit → tune.run
(tune/tune.py:131) → TrialRunner.step (execution/trial_runner.py:962) with
one Trainable actor per trial (execution/ray_trial_executor.py:350).
Here the executor is folded into the runner: trials are ray_tpu actors,
results stream back as object refs, schedulers/searchers see every result.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Type, Union

logger = logging.getLogger(__name__)

import ray_tpu
from ray_tpu.tune import search as search_mod
from ray_tpu.tune.sample import Domain, GridSearch
from ray_tpu.tune.schedulers import (CONTINUE, STOP, FIFOScheduler,
                                     TrialScheduler)
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.trainable import (DONE, TRAINING_ITERATION, Trainable,
                                    wrap_function)
from ray_tpu.tune.trial import (ERROR, PENDING, RUNNING, TERMINATED, Trial)


class _TrialActorShim:
    """The per-trial actor: hosts the Trainable instance."""

    def create(self, trainable_cls, config, start_iteration: int = 0) -> bool:
        self._t = trainable_cls(config)
        # restart continuity: training_iteration keeps counting across
        # failure-restarts (function trainables don't persist it themselves)
        if start_iteration:
            self._t._iteration = start_iteration
        return True

    def train(self) -> Dict[str, Any]:
        return self._t.train()

    def save(self):
        return self._t.save()

    def restore(self, ckpt) -> bool:
        self._t.restore(ckpt)
        return True

    def reset(self, config) -> bool:
        return bool(self._t.reset_config(config))

    def stop(self) -> bool:
        self._t.stop()
        return True


_TrialActor = ray_tpu.remote(_TrialActorShim)


class TrialRunner:
    def __init__(self, trainable_cls: Type[Trainable],
                 searcher: Searcher,
                 scheduler: Optional[TrialScheduler] = None,
                 *,
                 experiment_name: str = "exp",
                 metric: Optional[str] = None, mode: str = "max",
                 stop: Optional[Dict[str, Any]] = None,
                 max_concurrent: int = 4,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 checkpoint_freq: int = 0,
                 num_to_keep: Optional[int] = None,
                 max_failures: int = 0,
                 callbacks: Optional[List] = None,
                 local_dir: Optional[str] = None):
        self.trainable_cls = trainable_cls
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_search_properties(metric, mode)
        self.experiment_name = experiment_name
        self.metric, self.mode = metric, mode
        self.stop_criteria = dict(stop or {})
        self.max_concurrent = max_concurrent
        self.resources_per_trial = resources_per_trial or {"CPU": 1.0}
        self.checkpoint_freq = checkpoint_freq
        self.num_to_keep = num_to_keep
        self.max_failures = max_failures
        self.callbacks = callbacks or []
        self.trials: List[Trial] = []
        self._exhausted = False
        self.experiment_dir: Optional[str] = None
        if local_dir is not None:
            self.experiment_dir = os.path.join(
                os.path.expanduser(local_dir), experiment_name)
            os.makedirs(self.experiment_dir, exist_ok=True)
        for cb in self.callbacks:
            if hasattr(cb, "setup"):
                cb.setup(experiment_dir=self.experiment_dir)

    # ------------------------------------------------- experiment state

    def _snapshot(self, force: bool = False):
        """Persist resumable experiment state (reference:
        tune/execution/trial_runner.py checkpoint + experiment_state-*.json
        in the experiment dir). Throttled: trials carry their checkpoint
        payloads in-memory, so a snapshot can be large — rewriting it on
        every result would stall the driver."""
        if self.experiment_dir is None:
            return
        now = time.time()
        period = float(os.environ.get("RTPU_TUNE_SNAPSHOT_PERIOD", "10"))
        if not force and now - getattr(self, "_last_snapshot", 0.0) < period:
            return
        self._last_snapshot = now
        try:
            payload = {"trials": self.trials, "exhausted": self._exhausted,
                       "searcher": self.searcher,
                       "scheduler": self.scheduler,
                       "settings": {
                           "checkpoint_freq": self.checkpoint_freq,
                           "num_to_keep": self.num_to_keep,
                           "max_failures": self.max_failures,
                           "stop": self.stop_criteria,
                           "metric": self.metric, "mode": self.mode,
                       },
                       "timestamp": now}
            tmp = os.path.join(self.experiment_dir,
                               ".experiment_state.pkl.tmp")
            with open(tmp, "wb") as f:
                pickle.dump(payload, f)
            os.replace(tmp, os.path.join(self.experiment_dir,
                                         "experiment_state.pkl"))
        except Exception:
            return  # unpicklable user objects: skip resumability, not runs
        summary = [{"trial_id": t.trial_id, "name": t.trial_name,
                    "status": t.status, "iterations": len(t.results),
                    "last_result": {
                        k: v for k, v in (t.last_result or {}).items()
                        if isinstance(v, (int, float, str, bool))}}
                   for t in self.trials]
        with open(os.path.join(self.experiment_dir,
                               "experiment_state.json"), "w") as f:
            json.dump(summary, f, indent=1)

    def restore_from_dir(self, experiment_dir: str):
        """Rebuild trials from a prior run's snapshot; unfinished trials
        restart from their latest checkpoint."""
        path = os.path.join(experiment_dir, "experiment_state.pkl")
        with open(path, "rb") as f:
            payload = pickle.load(f)
        self.trials = payload["trials"]
        self._exhausted = payload["exhausted"]
        self.searcher = payload["searcher"]
        if payload.get("scheduler") is not None:
            # keep ASHA rungs / PBT scores etc. across the resume
            self.scheduler = payload["scheduler"]
        # the original run's settings win over the restoring runner's
        # defaults (Tuner.restore only knows the path)
        s = payload.get("settings", {})
        self.checkpoint_freq = s.get("checkpoint_freq",
                                     self.checkpoint_freq)
        self.num_to_keep = s.get("num_to_keep", self.num_to_keep)
        self.max_failures = s.get("max_failures", self.max_failures)
        self.stop_criteria = s.get("stop", self.stop_criteria)
        self.metric = s.get("metric", self.metric)
        self.mode = s.get("mode", self.mode)
        for t in self.trials:
            if t.status in (RUNNING, PENDING):
                t.status = PENDING
        # keep new trial names/dirs collision-free across the resume
        import itertools
        from ray_tpu.tune import trial as trial_mod
        maxi = max((t.index for t in self.trials), default=-1)
        trial_mod._trial_counter = itertools.count(maxi + 1)

    # ------------------------------------------------------------- helpers

    def get_trial(self, trial_id: str) -> Optional[Trial]:
        for t in self.trials:
            if t.trial_id == trial_id:
                return t
        return None

    def _live_trials(self) -> List[Trial]:
        return [t for t in self.trials if t.status in (PENDING, RUNNING)]

    def _running(self) -> List[Trial]:
        return [t for t in self.trials if t.status == RUNNING]

    # -------------------------------------------------------------- driving

    def _maybe_create_trials(self):
        while (not self._exhausted and
               len(self._live_trials()) < self.max_concurrent):
            tentative = Trial({}, self.experiment_name)
            config = self.searcher.suggest(tentative.trial_id)
            if config is search_mod.PENDING:
                break
            if config is None:
                self._exhausted = True
                break
            trial = tentative
            trial.config = config
            trial.resources = dict(self.resources_per_trial)
            trial.max_failures = self.max_failures
            trial.ckpt_manager.num_to_keep = self.num_to_keep
            trial.ckpt_manager.metric = self.metric
            trial.ckpt_manager.mode = self.mode
            if self.experiment_dir is not None:
                trial.logdir = os.path.join(self.experiment_dir,
                                            trial.trial_name)
                os.makedirs(trial.logdir, exist_ok=True)
            self.trials.append(trial)

    def _start_trial(self, trial: Trial, checkpoint=None):
        opts: Dict[str, Any] = {}
        custom: Dict[str, float] = {}
        for k, v in trial.resources.items():
            if k == "CPU":
                opts["num_cpus"] = v
            elif k == "GPU":
                opts["num_gpus"] = v
            elif k == "TPU":
                opts["num_tpus"] = v
            elif k == "memory":
                opts["memory"] = v
            else:
                custom[k] = v
        if custom:
            opts["resources"] = custom
        trial.actor = _TrialActor.options(**opts).remote()
        cfg = dict(trial.config)
        cfg["__trial_id__"] = trial.trial_id
        cfg["__trial_name__"] = trial.trial_name
        if checkpoint is not None:
            cfg["__checkpoint__"] = checkpoint
        # NO blocking gets here: per-actor call ordering sequences
        # create -> restore -> train on the actor, and the actor itself
        # may still be PENDING_CREATION behind running trials' resources.
        # A synchronous wait at this point deadlocks the runner: it can
        # never process the running trials' results, so the resources the
        # pending actor needs are never released (observed as a hang the
        # moment trials exceed cluster CPUs with prestarted workers).
        setup_refs = [trial.actor.create.remote(
            self.trainable_cls, cfg, len(trial.results))]
        if checkpoint is not None:
            setup_refs.append(trial.actor.restore.remote(checkpoint))
        # checked when train's first result lands (_check_setup_refs):
        # by per-actor ordering they are resolved by then, so a failed
        # restore surfaces as a trial failure instead of silently
        # training from scratch
        trial.setup_refs = setup_refs
        trial.status = RUNNING
        trial.future = trial.actor.train.remote()
        for cb in self.callbacks:
            cb.on_trial_start(trial)

    def _stop_trial(self, trial: Trial, status: str = TERMINATED):
        if trial.actor is not None:
            try:
                trial.actor.stop.remote()
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
        trial.future = None
        trial.status = status
        self.searcher.on_trial_complete(
            trial.trial_id, trial.last_result, error=(status == ERROR))
        self.scheduler.on_trial_complete(self, trial, trial.last_result)
        for cb in self.callbacks:
            cb.on_trial_complete(trial)

    def _should_stop_trial(self, trial: Trial, result: Dict) -> bool:
        if result.get(DONE):
            return True
        for key, bound in self.stop_criteria.items():
            if key in result and float(result[key]) >= float(bound):
                return True
        return False

    def _save_checkpoint(self, trial: Trial, result: Dict):
        ckpt = result.pop("__checkpoint__", None)
        if ckpt is None and self.checkpoint_freq and \
                result.get(TRAINING_ITERATION, 0) % self.checkpoint_freq == 0:
            try:
                ckpt = ray_tpu.get(trial.actor.save.remote())
            except Exception:
                ckpt = None
        if ckpt is not None:
            ckpt = self._persist_checkpoint(trial, ckpt, result)
            trial.ckpt_manager.add(ckpt, result)

    def _persist_checkpoint(self, trial: Trial, ckpt, result: Dict):
        """Route trial checkpoints through the durable engine
        (<logdir>/checkpoints, atomic commit): a driver crash between
        result rounds can no longer lose every checkpoint with the
        process. Disk retention by recency only applies when no metric is
        set — score-based top-K stays the in-memory manager's call.
        RTPU_TUNE_DISK_CKPT=0 restores the in-memory-only behavior."""
        if trial.logdir is None or \
                os.environ.get("RTPU_TUNE_DISK_CKPT", "1") == "0":
            return ckpt
        try:
            from ray_tpu.checkpoint import CheckpointManager
            mgr = getattr(trial, "_disk_ckpt_mgr", None)
            if mgr is None:
                mgr = CheckpointManager(
                    os.path.join(trial.logdir, "checkpoints"),
                    num_to_keep=(self.num_to_keep
                                 if self.metric is None else None))
                trial._disk_ckpt_mgr = mgr
            latest = mgr.latest_committed()
            step = max(result.get(TRAINING_ITERATION, 0),
                       (latest + 1) if latest is not None else 0)
            mgr.stage(step, ckpt)
            mgr.commit_step(step)
            return mgr.load(step)
        except Exception as e:  # noqa: BLE001 — durability is best-effort
            # here; the in-band payload still reaches the in-memory manager
            logger.warning("trial %s: disk checkpoint persist failed: %r",
                           trial.trial_id, e)
            return ckpt

    def _check_setup_refs(self, trial: Trial) -> bool:
        """Surface create/restore errors once train has produced its
        first signal (actor ordering guarantees they resolved first).
        True = setup was clean."""
        refs, trial.setup_refs = getattr(trial, "setup_refs", None), None
        if not refs:
            return True
        try:
            ray_tpu.get(refs, timeout=10)
            return True
        except Exception as e:
            self._process_failure(trial, e)
            return False

    def _process_result(self, trial: Trial, result: Dict[str, Any]):
        if not self._check_setup_refs(trial):
            return
        auto_keys = {DONE, TRAINING_ITERATION, "time_total_s",
                     "__checkpoint__"}
        if result.get(DONE) and not (set(result) - auto_keys):
            # terminal sentinel from a finished function trainable — don't
            # let it clobber last_result's metrics
            self._stop_trial(trial, TERMINATED)
            return
        trial.results.append(result)
        self.searcher.on_trial_result(trial.trial_id, result)
        # pops the in-band __checkpoint__ payload so loggers see a clean
        # metrics dict
        self._save_checkpoint(trial, result)
        for cb in self.callbacks:
            cb.on_trial_result(trial, result)
        if self._should_stop_trial(trial, result):
            # checkpoint-at-end so stop-criteria trials don't finish bare
            if self.checkpoint_freq and not result.get(DONE):
                try:
                    ckpt = ray_tpu.get(trial.actor.save.remote())
                    trial.ckpt_manager.add(ckpt, result)
                except Exception:
                    pass
            self._stop_trial(trial, TERMINATED)
            return
        fut_before = trial.future
        decision = self.scheduler.on_trial_result(self, trial, result)
        if decision == STOP:
            self._stop_trial(trial, TERMINATED)
        elif trial.future is fut_before:
            # a PBT exploit may have restarted the actor and queued its
            # first train() already — don't double-schedule
            trial.future = trial.actor.train.remote()

    def _process_failure(self, trial: Trial, err: BaseException):
        trial.error = "".join(traceback.format_exception_only(
            type(err), err))
        trial.num_failures += 1
        if trial.num_failures <= trial.max_failures:
            # restart from the latest checkpoint (reference:
            # trial_runner.py:1336 restore-on-failure path)
            ckpt = trial.latest_checkpoint
            try:
                if trial.actor is not None:
                    ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
            try:
                self._start_trial(trial, checkpoint=ckpt)
            except Exception as restart_err:
                trial.error += f"\nrestart failed: {restart_err!r}"
                self._stop_trial(trial, ERROR)
        else:
            self._stop_trial(trial, ERROR)

    # PBT exploit hook (called by the scheduler)
    def exploit(self, trial: Trial, donor: Trial,
                new_config: Dict[str, Any]):
        ckpt = donor.latest_checkpoint
        if ckpt is None:
            return
        trial.config = new_config
        in_place = False
        try:
            in_place = ray_tpu.get(trial.actor.reset.remote(new_config))
        except Exception:
            in_place = False
        if in_place:
            ray_tpu.get(trial.actor.restore.remote(ckpt))
        else:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
            trial.actor = None
            self._start_trial(trial, checkpoint=ckpt)
        trial.ckpt_manager.add(ckpt, donor.last_result or {})

    # ResourceChangingScheduler hook (called by the scheduler)
    def update_trial_resources(self, trial: Trial,
                               resources: Dict[str, float]):
        """Restart the trial's actor with new resources from its own
        latest checkpoint (reference: ray_trial_executor's
        resource-update path used by ResourceChangingScheduler)."""
        if dict(trial.resources) == dict(resources):
            return False
        ckpt = trial.latest_checkpoint
        if ckpt is None:
            # restarting without a checkpoint would discard all progress
            logger.warning(
                "skipping resource update for %s: no checkpoint yet "
                "(set checkpoint_freq>=1 to let resources change)",
                trial.trial_id)
            return False
        try:
            if trial.actor is not None:
                ray_tpu.kill(trial.actor)
        except Exception:
            pass
        trial.actor = None
        trial.resources = dict(resources)
        self._start_trial(trial, checkpoint=ckpt)
        return True

    # ---------------------------------------------------------------- loop

    def step(self):
        self._maybe_create_trials()
        for trial in self.trials:
            if trial.status == PENDING and trial.actor is None:
                try:
                    # resumed trials restart from their latest checkpoint
                    self._start_trial(trial,
                                      checkpoint=trial.latest_checkpoint)
                except Exception as e:
                    self._process_failure(trial, e)
        futures = {t.future: t for t in self._running()
                   if t.future is not None}
        if not futures:
            return
        ready, _ = ray_tpu.wait(list(futures), num_returns=1, timeout=30.0)
        for ref in ready:
            trial = futures[ref]
            try:
                result = ray_tpu.get(ref)
            except Exception as e:
                self._process_failure(trial, e)
                continue
            self._process_result(trial, result)

    def is_finished(self) -> bool:
        return self._exhausted and not self._live_trials()

    def run_all(self):
        while not self.is_finished():
            self.step()
            self._snapshot()
        self._snapshot(force=True)
        for cb in self.callbacks:
            if hasattr(cb, "on_experiment_end"):
                cb.on_experiment_end(self.trials)
        return self.trials


# ---------------------------------------------------------------------------


def run(trainable: Union[Callable, Type[Trainable]],
        *,
        config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1,
        metric: Optional[str] = None,
        mode: str = "max",
        stop: Optional[Dict[str, Any]] = None,
        search_alg: Optional[Searcher] = None,
        scheduler: Optional[TrialScheduler] = None,
        max_concurrent_trials: int = 4,
        resources_per_trial: Optional[Dict[str, float]] = None,
        checkpoint_freq: int = 0,
        keep_checkpoints_num: Optional[int] = None,
        max_failures: int = 0,
        name: str = "exp",
        callbacks: Optional[List] = None,
        local_dir: Optional[str] = None,
        sync_config=None,
        resume: bool = False,
        verbose: int = 0) -> "ExperimentAnalysis":
    """The reference's tune.run (tune/tune.py:131)."""
    from ray_tpu._private import usage as _usage
    _usage.record_library_usage("tune")
    config = config or {}
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        trainable_cls = trainable
    elif callable(trainable):
        trainable_cls = wrap_function(trainable)
    else:
        raise TypeError(f"trainable must be a function or Trainable subclass,"
                        f" got {type(trainable)}")

    if search_alg is None:
        search_alg = BasicVariantGenerator(config, num_samples=num_samples,
                                           metric=metric, mode=mode)
    else:
        search_alg.set_search_properties(metric, mode, config)

    if local_dir is None:
        local_dir = os.environ.get(
            "RTPU_RESULTS_DIR", os.path.expanduser("~/ray_tpu_results"))
    if callbacks is None:
        from ray_tpu.tune.logger import default_callbacks
        callbacks = default_callbacks()
    if sync_config is not None and getattr(
            sync_config, "upload_dir", None):
        from ray_tpu.tune.syncer import SyncerCallback
        callbacks = list(callbacks) + [SyncerCallback(sync_config)]

    runner = TrialRunner(
        trainable_cls, search_alg, scheduler,
        experiment_name=name, metric=metric, mode=mode, stop=stop,
        max_concurrent=max_concurrent_trials,
        resources_per_trial=resources_per_trial,
        checkpoint_freq=checkpoint_freq,
        num_to_keep=keep_checkpoints_num,
        max_failures=max_failures, callbacks=callbacks,
        local_dir=local_dir)
    if resume:
        state = os.path.join(runner.experiment_dir or "",
                             "experiment_state.pkl")
        if not os.path.exists(state):
            # a silent fall-through would rerun the whole sweep from
            # scratch while the caller believes they resumed
            raise FileNotFoundError(
                f"resume requested but no experiment state at {state!r}")
        runner.restore_from_dir(runner.experiment_dir)
    trials = runner.run_all()
    return ExperimentAnalysis(trials, metric=metric, mode=mode)


class ExperimentAnalysis:
    """Result accessor (reference: tune/analysis/experiment_analysis.py)."""

    def __init__(self, trials: List[Trial], metric: Optional[str] = None,
                 mode: str = "max"):
        self.trials = trials
        self.default_metric, self.default_mode = metric, mode

    def get_best_trial(self, metric: Optional[str] = None,
                       mode: Optional[str] = None,
                       scope: str = "last") -> Optional[Trial]:
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        sign = 1.0 if mode == "max" else -1.0
        best, best_v = None, None
        for t in self.trials:
            hist = t.metric_history(metric)
            if not hist:
                continue
            candidates = hist if scope == "all" else hist[-1:]
            v = max(sign * h for h in candidates)
            if best_v is None or v > best_v:
                best, best_v = t, v
        return best

    @property
    def best_trial(self) -> Optional[Trial]:
        return self.get_best_trial()

    @property
    def best_config(self) -> Optional[Dict[str, Any]]:
        t = self.best_trial
        return t.config if t else None

    @property
    def best_result(self) -> Optional[Dict[str, Any]]:
        t = self.best_trial
        return t.last_result if t else None

    @property
    def best_checkpoint(self):
        t = self.best_trial
        return t.best_checkpoint if t else None

    def dataframe(self):
        import pandas as pd
        rows = []
        for t in self.trials:
            row = dict(t.last_result or {})
            row["trial_id"] = t.trial_id
            row["status"] = t.status
            for k, v in t.config.items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)

    @property
    def results(self) -> List[Optional[Dict[str, Any]]]:
        return [t.last_result for t in self.trials]
