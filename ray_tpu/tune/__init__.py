"""ray_tpu.tune — hyperparameter search / experiment engine.

Reference: python/ray/tune — Tuner (tuner.py:44), tune.run (tune/tune.py:131),
Trainable (trainable/trainable.py:66), searchers (search/), schedulers
(schedulers/). Train and RLlib ride on this layer, as in the reference.
"""

from ray_tpu.tune.sample import (  # noqa: F401
    choice, grid_search, loguniform, quniform, randint, randn, sample_from,
    uniform)
from ray_tpu.tune.trainable import Trainable  # noqa: F401
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator, BayesOptSearch, BOHBSearcher,
    ConcurrencyLimiter, HyperOptSearch, OptunaSearch, RandomSearch,
    Searcher, TPESearcher)
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler, AsyncHyperBandScheduler, FIFOScheduler,
    HyperBandForBOHB, HyperBandScheduler, MedianStoppingRule, PB2,
    PopulationBasedTraining, ResourceChangingScheduler, TrialScheduler)
from ray_tpu.tune.logger import (  # noqa: F401
    Callback, CSVLoggerCallback, JsonLoggerCallback, LoggerCallback,
    TBXLoggerCallback)
from ray_tpu.tune.syncer import (  # noqa: F401
    LocalSyncer, SyncConfig, Syncer, SyncerCallback)
from ray_tpu.tune.trial import Trial  # noqa: F401
from ray_tpu.tune.tune import ExperimentAnalysis, TrialRunner, run  # noqa: F401
from ray_tpu.tune.tuner import (  # noqa: F401
    Result, ResultGrid, TuneConfig, Tuner)

# session.report works inside function trainables too (reference: air.session)
from ray_tpu.air.session import report  # noqa: F401
