"""Search-space primitives (reference: python/ray/tune/search/sample.py).

Usage parity with the reference:
    param_space = {"lr": tune.loguniform(1e-5, 1e-2),
                   "layers": tune.grid_search([2, 4, 8]),
                   "seed": tune.randint(0, 10_000)}
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Sequence


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Uniform(Domain):
    def __init__(self, lower: float, upper: float):
        self.lower, self.upper = float(lower), float(upper)

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


class LogUniform(Domain):
    def __init__(self, lower: float, upper: float, base: float = 10.0):
        import math
        assert lower > 0 and upper > lower
        self.lower, self.upper, self.base = lower, upper, base
        self._log = (math.log(lower, base), math.log(upper, base))

    def sample(self, rng):
        return self.base ** rng.uniform(*self._log)


class Randint(Domain):
    """[lower, upper) like the reference's tune.randint."""

    def __init__(self, lower: int, upper: int):
        self.lower, self.upper = int(lower), int(upper)

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


class QUniform(Domain):
    def __init__(self, lower: float, upper: float, q: float):
        self.lower, self.upper, self.q = lower, upper, q

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return round(v / self.q) * self.q


class Normal(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


class GridSearch:
    """Marker expanded exhaustively by BasicVariantGenerator (cross product
    with other grid axes; reference: search/basic_variant.py)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float, base: float = 10.0) -> LogUniform:
    return LogUniform(lower, upper, base)


def randint(lower: int, upper: int) -> Randint:
    return Randint(lower, upper)


def quniform(lower: float, upper: float, q: float) -> QUniform:
    return QUniform(lower, upper, q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def resolve(space: Dict[str, Any], rng: random.Random) -> Dict[str, Any]:
    """Sample every Domain leaf; GridSearch must already be expanded."""
    out = {}
    for k, v in space.items():
        if isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, GridSearch):
            raise ValueError(
                f"unexpanded grid_search for {k!r} (searchers other than "
                "BasicVariantGenerator don't support grid_search)")
        elif isinstance(v, dict):
            out[k] = resolve(v, rng)
        else:
            out[k] = v
    return out


def expand_grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cross-product of all GridSearch axes (nested dicts included);
    Domain leaves are left in place for later sampling."""
    import itertools

    paths: List[tuple] = []
    values: List[List[Any]] = []

    def walk(d: Dict[str, Any], prefix: tuple):
        for k, v in d.items():
            if isinstance(v, GridSearch):
                paths.append(prefix + (k,))
                values.append(v.values)
            elif isinstance(v, dict):
                walk(v, prefix + (k,))

    walk(space, ())
    if not paths:
        return [dict(space)]

    def set_path(d, path, value):
        for p in path[:-1]:
            d = d[p]
        d[path[-1]] = value

    import copy
    out = []
    for combo in itertools.product(*values):
        variant = copy.deepcopy(space)
        for path, value in zip(paths, combo):
            set_path(variant, path, value)
        out.append(variant)
    return out
