"""Trainable API + function-trainable wrapper.

Reference semantics: tune/trainable/trainable.py:66 (class API — setup/
step/save/restore, train():320 drives one iteration) and
tune/trainable/function_trainable.py:284 (function API — the user fn runs
in a thread, session.report() yields results back to the driver).
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air import session as air_session

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class Trainable:
    """Class API: subclass and override setup/step/save_checkpoint/
    load_checkpoint."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self._iteration = 0
        self._start_time = time.time()
        self.setup(self.config)

    # -- override points ----------------------------------------------------

    def setup(self, config: Dict[str, Any]):
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Dict[str, Any]:
        return {}

    def load_checkpoint(self, state: Dict[str, Any]):
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Return True if the trainable reconfigures in-place (PBT exploit
        without an actor restart — reference: trainable.py reset_config)."""
        return False

    def cleanup(self):
        pass

    # -- driver-facing ------------------------------------------------------

    def train(self) -> Dict[str, Any]:
        result = self.step() or {}
        self._iteration += 1
        result.setdefault(TRAINING_ITERATION, self._iteration)
        result.setdefault("time_total_s", time.time() - self._start_time)
        result.setdefault(DONE, False)
        return result

    def save(self) -> Checkpoint:
        state = self.save_checkpoint() or {}
        state["_iteration"] = self._iteration
        return Checkpoint.from_dict(state)

    def restore(self, checkpoint: Checkpoint):
        state = checkpoint.to_dict()
        # only class-API checkpoints carry _iteration; function-API
        # checkpoints rely on the runner seeding start_iteration, which
        # must not be clobbered here
        if "_iteration" in state:
            self._iteration = state.pop("_iteration")
        self.load_checkpoint(state)

    def stop(self):
        self.cleanup()


class FunctionTrainable(Trainable):
    """Wraps ``fn(config)`` (or ``fn(config, checkpoint)``): runs it in a
    thread with an installed session; each session.report() becomes one
    train() result."""

    _fn: Callable = None  # set by subclass factory

    def setup(self, config: Dict[str, Any]):
        self._session = air_session._Session(
            trial_id=config.pop("__trial_id__", ""),
            trial_name=config.pop("__trial_name__", ""),
            checkpoint=config.pop("__checkpoint__", None))
        self._error: Optional[str] = None
        self._thread_done = threading.Event()

        def runner():
            air_session._set_session(self._session)
            try:
                self._fn(dict(config))
            except Exception:
                self._error = traceback.format_exc()
            finally:
                self._thread_done.set()
                # unblock a train() waiting on the queue
                self._session.result_queue.put(None)

        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()

    def step(self) -> Dict[str, Any]:
        item = self._session.result_queue.get()
        if item is None:
            if self._error:
                raise RuntimeError(f"trainable function failed:\n"
                                   f"{self._error}")
            return {DONE: True}
        result = dict(item.metrics)
        if item.checkpoint is not None:
            result["__checkpoint__"] = item.checkpoint
        return result

    def save_checkpoint(self) -> Dict[str, Any]:
        # function API checkpoints travel inside results via session.report
        return {}

    def load_checkpoint(self, state):
        pass

    def cleanup(self):
        self._session.stop_event.set()


def wrap_function(fn: Callable) -> type:
    """Build a FunctionTrainable subclass bound to ``fn``."""
    return type(f"func_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})
