"""Searchers: config suggestion strategies.

Reference: tune/search/ — basic_variant.py (grid + random, the default),
searcher ABC (search/searcher.py), ConcurrencyLimiter (search/search_
generator.py). The optimization-library searchers (optuna/hyperopt/...) are
soft-gated the way the reference soft-imports them.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.sample import expand_grid, resolve

# Sentinel: searcher not ready to suggest yet (at capacity) — distinct from
# None, which means the search space is exhausted.
PENDING = object()


class Searcher:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode

    def set_search_properties(self, metric, mode, space) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        """Next config, or None when exhausted."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples random repeats (the default
    searcher; reference search/basic_variant.py)."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._rng = random.Random(seed)
        self._variants: List[Dict[str, Any]] = []
        for _ in range(num_samples):
            self._variants.extend(expand_grid(space))
        self._next = 0

    @property
    def total_trials(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._next >= len(self._variants):
            return None
        variant = self._variants[self._next]
        self._next += 1
        return resolve(variant, self._rng)


class RandomSearch(Searcher):
    """Pure random sampling of a Domain-only space (no grid axes)."""

    def __init__(self, space: Dict[str, Any], num_samples: int,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._space = space
        self._remaining = num_samples
        self._rng = random.Random(seed)

    def suggest(self, trial_id):
        if self._remaining <= 0:
            return None
        self._remaining -= 1
        return resolve(self._space, self._rng)


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (reference: search/concurrency_limiter)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(metric=searcher.metric, mode=searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set = set()

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return PENDING  # runner retries later
        config = self.searcher.suggest(trial_id)
        if config is not None:
            self._live.add(trial_id)
        return config

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)
