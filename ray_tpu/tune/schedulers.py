"""Trial schedulers: early stopping + population-based training.

Reference: tune/schedulers/ — async_hyperband.py (ASHA, the workhorse),
median_stopping_rule.py, pbt.py, hyperband.py. Decisions are made per
result: CONTINUE / STOP / and for PBT an exploit-mutate step.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"

TRAINING_ITERATION = "training_iteration"


class TrialScheduler:
    def set_search_properties(self, metric: Optional[str], mode: str):
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode

    def on_trial_result(self, runner, trial, result) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial, result):
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (default)."""

    metric: Optional[str] = None
    mode: str = "max"


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: schedulers/async_hyperband.py).

    At each rung (iteration = grace_period * reduction_factor^k) a trial
    must beat the rung's top 1/reduction_factor cutoff of previously
    recorded results or it is stopped. Asynchronous: no waiting for a full
    bracket — decisions use whatever has been recorded so far.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = TRAINING_ITERATION,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        # rung iteration -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = {}
        r = grace_period
        while r < max_t:
            self._rungs[int(r)] = []
            r *= reduction_factor

    def _val(self, result) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return float(v) if self.mode == "max" else -float(v)

    def on_trial_result(self, runner, trial, result) -> str:
        t = result.get(self.time_attr)
        if t is None or self.metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        rung = self._current_rung(t)
        if rung is None:
            return CONTINUE
        v = self._val(result)
        if v is None:
            return CONTINUE
        recorded = self._rungs[rung]
        recorded.append(v)
        k = max(1, int(math.ceil(len(recorded) / self.rf)))
        cutoff = sorted(recorded, reverse=True)[k - 1]
        if v < cutoff:
            return STOP
        return CONTINUE

    def _current_rung(self, t: int) -> Optional[int]:
        best = None
        for r in self._rungs:
            if t >= r and (best is None or r > best):
                best = r
        return best


# Alias matching the reference's exported name
ASHAScheduler = AsyncHyperBandScheduler


class HyperBandScheduler(TrialScheduler):
    """HyperBand (reference: schedulers/hyperband.py).

    Incoming trials round-robin into brackets s = s_max..0; bracket s
    starts its trials with grace period eta^s — bracket 0 culls most
    aggressively (grace 1), bracket s_max (grace ≈ max_t) runs its
    trials essentially to full budget, preserving HyperBand's
    no-one-regime-wins-everywhere guarantee. Successive-halving rungs
    cull to the top 1/eta within the bracket. Decisions are made
    asynchronously per result (no global pause barrier — the
    ASHA-style relaxation of the synchronous algorithm, which composes
    with this runner's streaming result loop)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = TRAINING_ITERATION,
                 max_t: int = 81, reduction_factor: float = 3):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.max_t = max_t
        self.rf = reduction_factor
        # integer power loop: float log truncates (log(1000,10)=2.999…)
        # and would drop the full-budget bracket
        s_max, r = 0, 1
        while r * reduction_factor <= max_t:
            r *= reduction_factor
            s_max += 1
        # one ASHA ladder per bracket, with bracket-specific grace
        self._brackets = [
            AsyncHyperBandScheduler(
                metric=metric, mode=mode, time_attr=time_attr,
                max_t=max_t,
                grace_period=max(1, int(reduction_factor ** s)),
                reduction_factor=reduction_factor)
            for s in range(s_max, -1, -1)]
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def set_search_properties(self, metric, mode):
        super().set_search_properties(metric, mode)
        for b in self._brackets:
            b.set_search_properties(metric, mode)

    def _bracket_for(self, trial) -> "AsyncHyperBandScheduler":
        idx = self._assignment.get(trial.trial_id)
        if idx is None:
            idx = self._next % len(self._brackets)
            self._assignment[trial.trial_id] = idx
            self._next += 1
        return self._brackets[idx]

    def on_trial_result(self, runner, trial, result) -> str:
        return self._bracket_for(trial).on_trial_result(
            runner, trial, result)


class HyperBandForBOHB(HyperBandScheduler):
    """HyperBand variant paired with BOHBSearcher (reference:
    schedulers/hb_bohb.py). The bracket ladder is the async HyperBand
    above; BOHB's coupling lives in the SEARCHER (its KDE conditions on
    per-budget results arriving from these brackets), so this subclass
    exists as the documented pairing point and keeps the reference's
    class name."""


class ResourceChangingScheduler(TrialScheduler):
    """Wraps a base scheduler and reallocates per-trial resources while
    trials run (reference: schedulers/resource_changing_scheduler.py).

    ``resources_allocation_function(runner, trial, result, scheduler)``
    returns a resources dict (or None = keep); a change restarts the
    trial's actor from its latest checkpoint with the new allocation
    via runner.update_trial_resources."""

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function=None):
        self.base = base_scheduler or FIFOScheduler()
        self.alloc_fn = resources_allocation_function
        self.metric = getattr(self.base, "metric", None)
        self.mode = getattr(self.base, "mode", "max")

    def set_search_properties(self, metric, mode):
        super().set_search_properties(metric, mode)
        self.base.set_search_properties(metric, mode)

    def on_trial_result(self, runner, trial, result) -> str:
        decision = self.base.on_trial_result(runner, trial, result)
        if decision == STOP or self.alloc_fn is None:
            return decision
        new = self.alloc_fn(runner, trial, result, self)
        if new:
            runner.update_trial_resources(trial, new)
        return decision

    def on_trial_complete(self, runner, trial, result):
        self.base.on_trial_complete(runner, trial, result)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average is below the median of the other
    trials' running averages at the same point (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = TRAINING_ITERATION,
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        if self.metric is None or self.metric not in result:
            return CONTINUE
        v = float(result[self.metric])
        if self.mode == "min":
            v = -v
        hist = self._avgs.setdefault(trial.trial_id, [])
        hist.append(v)
        if result.get(self.time_attr, 0) < self.grace:
            return CONTINUE
        others = [sum(h) / len(h) for tid, h in self._avgs.items()
                  if tid != trial.trial_id and h]
        if len(others) < self.min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        mine = sum(hist) / len(hist)
        return STOP if mine < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py). Every perturbation_interval,
    bottom-quantile trials clone the checkpoint + config of a random
    top-quantile trial, with hyperparameters perturbed (x1.2 / x0.8 or
    resampled). The runner applies the exploit via trial restart/restore.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = TRAINING_ITERATION,
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.metric, self.mode = metric, mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}

    def on_trial_result(self, runner, trial, result) -> str:
        if self.metric is None or self.metric not in result:
            return CONTINUE
        v = float(result[self.metric])
        self._scores[trial.trial_id] = v if self.mode == "max" else -v
        t = result.get(self.time_attr, 0)
        if t - self._last_perturb.get(trial.trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t

        scores = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(scores)
        k = max(1, int(n * self.quantile))
        if n < 2 * k:
            return CONTINUE
        bottom = {tid for tid, _ in scores[:k]}
        top = [tid for tid, _ in scores[-k:]]
        if trial.trial_id not in bottom:
            return CONTINUE
        donor_id = self._rng.choice(top)
        donor = runner.get_trial(donor_id)
        if donor is None or donor.latest_checkpoint is None:
            return CONTINUE
        new_config = self._explore(dict(donor.config))
        runner.exploit(trial, donor, new_config)
        return CONTINUE

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.sample import Domain
        for key, mut in self.mutations.items():
            if key not in config:
                continue
            if isinstance(mut, Domain):
                if self._rng.random() < self.resample_prob:
                    config[key] = mut.sample(self._rng)
                else:
                    config[key] = config[key] * self._rng.choice([0.8, 1.2])
            elif isinstance(mut, (list, tuple)):
                config[key] = self._rng.choice(list(mut))
            elif callable(mut):
                config[key] = mut()
        return config


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: tune/schedulers/pb2.py).

    PBT's exploit step, but exploration picks new hyperparameters by
    maximizing a GP-UCB acquisition fit to (config → reward improvement)
    observations from the whole population, instead of random
    perturbation. The GP is the native one from search/bayesopt.py (the
    reference wraps GPy, which is not in this image).
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = TRAINING_ITERATION,
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[
                     Dict[str, List[float]]] = None,
                 quantile_fraction: float = 0.25,
                 log_scale_keys: Optional[List[str]] = None,
                 kappa: float = 2.0,
                 seed: Optional[int] = None):
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds: "
                             "{key: [min, max]}")
        super().__init__(
            metric=metric, mode=mode, time_attr=time_attr,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={}, quantile_fraction=quantile_fraction,
            seed=seed)
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.log_keys = set(log_scale_keys or [])
        self.kappa = kappa
        # observations: (warped config vector, normalized reward delta)
        self._X: List[List[float]] = []
        self._y: List[float] = []
        self._last_metric: Dict[str, float] = {}

    def _warp(self, key: str, v: float) -> float:
        lo, hi = self.bounds[key]
        if key in self.log_keys:
            lo, hi, v = math.log(lo), math.log(hi), math.log(max(v, 1e-12))
        return min(1.0, max(0.0, (v - lo) / (hi - lo)))

    def _unwarp(self, key: str, u: float) -> float:
        lo, hi = self.bounds[key]
        if key in self.log_keys:
            return math.exp(math.log(lo) + u * (math.log(hi) -
                                                math.log(lo)))
        return lo + u * (hi - lo)

    def on_trial_result(self, runner, trial, result) -> str:
        # record the reward delta this config produced since last result
        if self.metric is not None and self.metric in result:
            v = float(result[self.metric])
            if self.mode == "min":
                v = -v
            prev = self._last_metric.get(trial.trial_id)
            self._last_metric[trial.trial_id] = v
            if prev is not None and all(k in trial.config
                                        for k in self.bounds):
                x = [self._warp(k, float(trial.config[k]))
                     for k in sorted(self.bounds)]
                self._X.append(x)
                self._y.append(v - prev)
        config_before = id(trial.config)
        decision = super().on_trial_result(runner, trial, result)
        if id(trial.config) != config_before:
            # exploit happened: the next result's score jump comes from
            # the restored checkpoint, not the new config — recording it
            # as a delta would teach the GP a phantom improvement
            self._last_metric.pop(trial.trial_id, None)
        return decision

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        keys = sorted(self.bounds)
        if len(self._y) < 4:
            for k in keys:
                config[k] = self._unwarp(k, self._rng.random())
            return config
        import numpy as np
        from ray_tpu.tune.search.bayesopt import GP
        n_keep = 256  # recent window: the reward landscape is time-varying
        X = np.asarray(self._X[-n_keep:])
        y = np.asarray(self._y[-n_keep:])
        gp = GP(length_scale=0.3)
        gp.fit(X, y)
        cand = np.random.default_rng(
            self._rng.randrange(1 << 31)).random((128, len(keys)))
        mu, sd = gp.predict(cand)
        best = cand[int(np.argmax(mu + self.kappa * sd))]
        for k, u in zip(keys, best):
            config[k] = self._unwarp(k, float(u))
        return config
