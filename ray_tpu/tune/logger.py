"""Experiment callbacks + result loggers (CSV / JSON / TensorBoard).

Reference: tune/callback.py (Callback hooks), tune/logger/csv.py,
logger/json.py, logger/tensorboardx.py. Loggers run driver-side inside the
TrialRunner loop; each writes into trial.logdir.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, List, Optional

VALID_SUMMARY_TYPES = (int, float, bool)


class Callback:
    """Driver-side experiment hooks (reference: tune/callback.py:83)."""

    def setup(self, experiment_dir: Optional[str] = None):
        pass

    def on_trial_start(self, trial):
        pass

    def on_trial_result(self, trial, result: Dict[str, Any]):
        pass

    def on_trial_complete(self, trial):
        pass

    def on_experiment_end(self, trials: List) -> None:
        pass


def _json_default(o):
    try:
        import numpy as np
        if isinstance(o, (np.integer,)):
            return int(o)
        if isinstance(o, (np.floating,)):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:
        pass
    return str(o)


class LoggerCallback(Callback):
    """Base: tracks per-trial open state, closes on complete."""

    def on_trial_start(self, trial):
        if trial.logdir:
            os.makedirs(trial.logdir, exist_ok=True)
            self.log_trial_start(trial)

    def on_trial_result(self, trial, result):
        if trial.logdir:
            self.log_trial_result(trial, result)

    def on_trial_complete(self, trial):
        if trial.logdir:
            self.log_trial_end(trial)

    def log_trial_start(self, trial):
        pass

    def log_trial_result(self, trial, result):
        pass

    def log_trial_end(self, trial):
        pass


class JsonLoggerCallback(LoggerCallback):
    """params.json once + result.json (one JSON object per line).
    Reference: tune/logger/json.py."""

    def __init__(self):
        self._files: Dict[str, Any] = {}

    def log_trial_start(self, trial):
        # restarts (failure retry / PBT exploit) re-enter here: reuse the
        # open handle instead of leaking it
        with open(os.path.join(trial.logdir, "params.json"), "w") as f:
            json.dump(trial.config, f, default=_json_default)
        if trial.trial_id not in self._files:
            self._files[trial.trial_id] = open(
                os.path.join(trial.logdir, "result.json"), "a")

    def log_trial_result(self, trial, result):
        f = self._files.get(trial.trial_id)
        if f is None:
            return
        json.dump(result, f, default=_json_default)
        f.write("\n")
        f.flush()

    def log_trial_end(self, trial):
        f = self._files.pop(trial.trial_id, None)
        if f is not None:
            f.close()


class CSVLoggerCallback(LoggerCallback):
    """progress.csv with the first result's keys as the header.
    Reference: tune/logger/csv.py."""

    def __init__(self):
        self._writers: Dict[str, csv.DictWriter] = {}
        self._files: Dict[str, Any] = {}

    def log_trial_start(self, trial):
        if trial.trial_id in self._files:
            return  # trial restart: keep appending to the open file
        path = os.path.join(trial.logdir, "progress.csv")
        # resuming an experiment appends to an existing file: adopt its
        # header instead of writing a second one mid-stream
        fieldnames = None
        if os.path.exists(path) and os.path.getsize(path) > 0:
            with open(path, newline="") as existing:
                try:
                    fieldnames = next(csv.reader(existing))
                except StopIteration:
                    fieldnames = None
        self._files[trial.trial_id] = open(path, "a")
        if fieldnames:
            self._writers[trial.trial_id] = csv.DictWriter(
                self._files[trial.trial_id], fieldnames=fieldnames,
                extrasaction="ignore")

    def log_trial_result(self, trial, result):
        f = self._files.get(trial.trial_id)
        if f is None:
            return
        flat = {k: v for k, v in result.items()
                if isinstance(v, (*VALID_SUMMARY_TYPES, str))}
        w = self._writers.get(trial.trial_id)
        if w is None:
            w = csv.DictWriter(f, fieldnames=list(flat),
                               extrasaction="ignore")
            w.writeheader()
            self._writers[trial.trial_id] = w
        w.writerow({k: flat.get(k, "") for k in w.fieldnames})
        f.flush()

    def log_trial_end(self, trial):
        self._writers.pop(trial.trial_id, None)
        f = self._files.pop(trial.trial_id, None)
        if f is not None:
            f.close()


class TBXLoggerCallback(LoggerCallback):
    """TensorBoard events via tensorboardX.
    Reference: tune/logger/tensorboardx.py."""

    def __init__(self):
        self._writers: Dict[str, Any] = {}

    def log_trial_start(self, trial):
        if trial.trial_id in self._writers:
            return  # trial restart: keep the open writer
        from tensorboardX import SummaryWriter
        self._writers[trial.trial_id] = SummaryWriter(
            trial.logdir, flush_secs=10)

    def log_trial_result(self, trial, result):
        w = self._writers.get(trial.trial_id)
        if w is None:
            return
        step = result.get("training_iteration", 0)
        for k, v in result.items():
            if isinstance(v, VALID_SUMMARY_TYPES) and \
                    not isinstance(v, bool):
                w.add_scalar(f"ray/tune/{k}", float(v), global_step=step)
        w.flush()

    def log_trial_end(self, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            # final hparams summary so TB's HPARAMS tab has the trial
            flat = {k: v for k, v in trial.config.items()
                    if isinstance(v, (*VALID_SUMMARY_TYPES, str))}
            metrics = {k: v for k, v in (trial.last_result or {}).items()
                       if isinstance(v, VALID_SUMMARY_TYPES) and
                       not isinstance(v, bool)}
            if flat and metrics:
                try:
                    w.add_hparams(flat, metrics)
                except Exception:
                    pass
            w.close()


def default_callbacks() -> List[Callback]:
    """CSV + JSON always; TBX when tensorboardX imports (reference:
    DEFAULT_LOGGERS in tune/logger/__init__.py)."""
    cbs: List[Callback] = [CSVLoggerCallback(), JsonLoggerCallback()]
    try:
        import tensorboardX  # noqa: F401
        cbs.append(TBXLoggerCallback())
    except ImportError:
        pass
    return cbs
