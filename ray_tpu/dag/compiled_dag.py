"""Compiled actor DAGs: static graphs executed over pre-wired channels.

``node.compile()`` turns a ``.bind()``-built graph of actor methods into
a :class:`CompiledDAG`: actors are created (or reused) once, every
actor's address is resolved once, persistent peer-to-peer channels are
opened between consecutive stages (ray_tpu/dag/channel.py), and each
``execute()`` is a single trigger frame — intermediate results flow
stage-to-stage without returning to the driver, skipping the
owner→raylet→worker dispatch pipeline entirely.

Compilability (everything else transparently degrades to the dynamic
``.execute()`` path):

* every stage is an actor method (``ClassMethodNode``); plain-function
  nodes have no persistent process to pre-wire;
* each stage consumes exactly ONE upstream value (the ``InputNode`` or
  another stage); remaining bound args/kwargs are constants;
* actor constructors take constants only;
* every stage worker negotiated wire schema >= 1.5 (``__hello__``).

Failure model: a stage worker death tears the compiled graph down — the
raylet notices the dead worker and notifies the compiling owner
(``dag_peer_down``), in-flight invocations re-run on the dynamic path
(each invocation returns exactly one result), and the next ``execute()``
re-compiles against fresh actors. See docs/COMPILED_DAGS.md.
"""

from __future__ import annotations

import logging
import os
import threading
import weakref
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import protocol, serialization, tracing
from ray_tpu._private.worker import global_worker
from ray_tpu.dag import channel as dagch
from ray_tpu.dag.dag_node import (ClassMethodNode, ClassNode, DAGNode,
                                  InputNode, MultiOutputNode)
from ray_tpu import exceptions as exc

logger = logging.getLogger(__name__)

_MIN_PEER_VERSION = (1, 5)  # dag channel frames joined the schema in 1.5

# dag_id -> weakref(CompiledDAG): routes dag_peer_down / dag_stage_error
# control-plane notifies (worker.py handlers) to the owning instance
_REGISTRY: Dict[str, "weakref.ref[CompiledDAG]"] = {}


class CompileError(Exception):
    """The graph cannot be compiled; callers fall back to dynamic."""


def on_peer_down(payload: Dict[str, Any]):
    ref = _REGISTRY.get(payload.get("dag_id") or "")
    cd = ref() if ref is not None else None
    if cd is not None:
        cd._on_channel_failure(
            f"stage worker {payload.get('worker_id', '?')} died")


def on_stage_error(payload: Dict[str, Any]):
    ref = _REGISTRY.get(payload.get("dag_id") or "")
    cd = ref() if ref is not None else None
    if cd is not None:
        cd._on_channel_failure(
            f"stage {payload.get('stage_id')} forward failed: "
            f"{payload.get('reason', '')}", seq=payload.get("seq"))


class _Invocation:
    """Driver-side state of one in-flight compiled execution."""

    __slots__ = ("event", "values", "error", "failed", "n_outputs",
                 "lock", "done", "_cb", "trace_span")

    def __init__(self, n_outputs: int):
        self.event = threading.Event()
        self.values: Dict[int, Any] = {}
        self.error: Optional[BaseException] = None
        self.failed: Optional[str] = None
        self.n_outputs = n_outputs
        self.lock = threading.Lock()
        self.done = False
        self._cb = None
        self.trace_span = None  # root span of this execution (1.6)

    # channel thread: decode one terminal output and maybe complete
    def deliver(self, index: int, payload: Dict[str, Any], plasma):
        try:
            value = dagch.decode_value(plasma, payload)
        except BaseException as e:  # noqa: BLE001 — app error envelope
            with self.lock:
                if self.done:
                    return
                self.error = e
                self.done = True
            self._complete()
            return
        with self.lock:
            if self.done:
                return
            self.values[index] = value
            if len(self.values) < self.n_outputs:
                return
            self.done = True
        self._complete()

    def fail(self, reason: str):
        with self.lock:
            if self.done:
                return  # result already arrived; late failure is noise
            self.failed = reason
            self.done = True
        self._complete()

    def _complete(self):
        self.event.set()
        cb, self._cb = self._cb, None
        if cb is not None:
            cb()

    def set_done_callback(self, cb):
        fire = False
        with self.lock:
            if self.done:
                fire = True
            else:
                self._cb = cb
        if fire:
            cb()


class _Watchdog:
    """One daemon thread arming timeouts for async invocations (a Timer
    per invocation would cost a thread each on the pipelined path)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: List[Any] = []  # (deadline, inv)
        self._thread: Optional[threading.Thread] = None

    def arm(self, inv: _Invocation, timeout: float):
        import time as _time
        with self._lock:
            self._armed.append((_time.monotonic() + timeout, inv))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="rtpu-dag-timeo")
                self._thread.start()

    def _run(self):
        import time as _time
        while True:
            _time.sleep(0.05)
            now = _time.monotonic()
            with self._lock:
                due = [x for x in self._armed if x[0] <= now or x[1].done]
                self._armed = [x for x in self._armed
                               if x[0] > now and not x[1].done]
            for _, inv in due:
                if not inv.done:
                    inv.fail("execute timed out")
            with self._lock:
                if not self._armed:
                    self._thread = None
                    return


_WATCHDOG = _Watchdog()


def _watchdog() -> _Watchdog:
    return _WATCHDOG


class _Stage:
    __slots__ = ("node", "stage_id", "upstream", "consumers", "out_index",
                 "actor", "address", "channel_address",
                 "channel_tcp_address", "trigger")

    def __init__(self, node: ClassMethodNode, stage_id: int):
        self.node = node
        self.stage_id = stage_id
        self.upstream: Optional[int] = None  # None = InputNode (entry)
        self.consumers: List[int] = []
        self.out_index: Optional[int] = None  # set on terminal stages
        self.actor = None
        self.address: Optional[str] = None
        self.channel_address: Optional[str] = None
        self.channel_tcp_address: str = ""  # 1.8: host:port twin
        self.trigger: Optional[dagch.FrameSocket] = None


class CompiledDAG:
    """A pre-wired execution graph. Create via ``DAGNode.compile()``.

    ``execute(x)`` returns the VALUE of the output node (a list for
    ``MultiOutputNode`` roots) — unlike dynamic ``.execute()``, which
    returns ObjectRefs: a compiled graph's results never become owned
    objects, they ride the channel straight back to the caller.
    """

    def __init__(self, root: DAGNode, *, ring_slots: int = 2,
                 buffer_size_bytes: int = 1 << 20,
                 execute_timeout_s: float = 30.0):
        self._root = root
        self._ring_slots = max(1, int(ring_slots))
        self._buffer_size = int(buffer_size_bytes)
        self._timeout_s = float(execute_timeout_s)
        self._base_id = os.urandom(8).hex()
        self._gen = 0
        self.dag_id = ""
        self._stages: List[_Stage] = []
        self._outputs: List[ClassMethodNode] = []
        self._compiled = False
        self._fallback_only = False
        self._seq = 0
        self._lock = threading.Lock()
        # in-flight window <= ring slots: a slot is only recycled once
        # the invocation that wrote it completed end-to-end, so capping
        # concurrency at the ring depth makes reuse race-free
        self._window = threading.BoundedSemaphore(self._ring_slots)
        self._compile_fail_at = 0.0
        self._trace_peers = False  # every stage peer negotiated >= 1.6
        try:
            self._analyze()
        except CompileError as e:
            # structurally uncompilable (function nodes, multi-upstream
            # stages, …): permanently dynamic — never retried
            logger.info("dag not compilable, running dynamic: %s", e)
            self._fallback_only = True
            return
        try:
            self._compile()
        except CompileError as e:
            # environmental (legacy peer, dead actor, channel refused):
            # run dynamic now, retry compilation later with backoff
            logger.info("dag compile degraded to dynamic execution: %s", e)
            self._note_compile_failure()

    # ------------------------------------------------------------ analysis

    def _analyze(self):
        if isinstance(self._root, MultiOutputNode):
            outputs = list(self._root._bound_args)
        elif isinstance(self._root, ClassMethodNode):
            outputs = [self._root]
        else:
            raise CompileError(
                "only actor-method graphs compile (root must be a "
                "ClassMethodNode or MultiOutputNode)")
        for o in outputs:
            if not isinstance(o, ClassMethodNode):
                raise CompileError(
                    f"output {type(o).__name__} is not an actor method")
        self._outputs = outputs

        order: List[ClassMethodNode] = []
        seen: Dict[int, _Stage] = {}

        def visit(node: ClassMethodNode):
            if id(node) in seen:
                return
            seen[id(node)] = None  # placeholder: cycle-safe
            up = self._upstream_of(node)
            if isinstance(up, ClassMethodNode):
                visit(up)
            order.append(node)

        for o in outputs:
            visit(o)
        stages = [_Stage(n, i) for i, n in enumerate(order)]
        by_node = {id(s.node): s for s in stages}
        for s in stages:
            up = self._upstream_of(s.node)
            if isinstance(up, ClassMethodNode):
                s.upstream = by_node[id(up)].stage_id
                by_node[id(up)].consumers.append(s.stage_id)
        for i, o in enumerate(outputs):
            st = by_node[id(o)]
            if st.out_index is not None:
                raise CompileError(
                    "the same stage appears twice in MultiOutputNode")
            st.out_index = i
        self._stages = stages

    @staticmethod
    def _upstream_of(node: ClassMethodNode) -> DAGNode:
        """The single data input of a stage (InputNode or upstream
        stage); everything else bound must be a constant."""
        ups = [a for a in node._bound_args if isinstance(a, DAGNode)]
        if any(isinstance(v, DAGNode) for v in node._bound_kwargs.values()):
            raise CompileError("DAG-valued kwargs are not compilable")
        if len(ups) != 1:
            raise CompileError(
                f"stage {node._method_name} must consume exactly one "
                f"upstream value, got {len(ups)}")
        up = ups[0]
        if not isinstance(up, (InputNode, ClassMethodNode)):
            raise CompileError(
                f"unsupported upstream node {type(up).__name__}")
        if isinstance(node._class_node, ClassNode) and \
                node._class_node._children():
            raise CompileError(
                "actor constructors must take constants only")
        return up

    # ------------------------------------------------------------- compile

    def _compile(self):
        w = global_worker()
        self._gen += 1
        self.dag_id = f"{self._base_id}.g{self._gen}"
        # one actor per ClassNode per CompiledDAG lifetime (the node
        # caches its handle; dead actors are invalidated + recreated).
        # The dead-check runs FIRST: a cached handle to a dead actor
        # still carries its stale worker address and would only fail at
        # channel open.
        cache: Dict[int, Any] = {}
        for s in self._stages:
            s.node._class_node._invalidate_if_dead()
        for s in self._stages:
            try:
                s.actor = s.node._class_node._execute_cached(cache, None)
                s.address = s.actor._resolve_address()
            except exc.ActorDiedError:
                s.node._class_node._invalidate_actor()
                s.actor = s.node._class_node._execute_cached({}, None)
                s.address = s.actor._resolve_address()

        ep = dagch.get_endpoint(w)
        opened: List[_Stage] = []
        min_peer: Optional[Tuple[int, int]] = None
        try:
            # open downstream-first so each stage learns its consumers'
            # channel addresses at open time
            for s in reversed(self._stages):
                downstream = []
                for c in s.consumers:
                    downstream.append({
                        "stage_id": c,
                        "address": self._stages[c].channel_address,
                        "tcp_address": self._stages[c].channel_tcp_address})
                if s.out_index is not None:
                    downstream.append({"address": ep.address, "sink": True,
                                       "tcp_address": ep.tcp_address,
                                       "index": s.out_index})
                payload = {
                    "dag_id": self.dag_id,
                    "stage_id": s.stage_id,
                    "method": s.node._method_name,
                    "args_tpl": self._args_template(s.node),
                    "kwargs_tpl": {
                        k: serialization.serialize(v).to_bytes()
                        for k, v in s.node._bound_kwargs.items()},
                    "downstream": downstream,
                    "owner_address": w.address,
                    "ring": {"slots": self._ring_slots,
                             "slot_bytes": self._buffer_size},
                }
                conn = w.io.run(w._peer(s.address))
                ver = self._negotiate(w, conn, s.address)
                if min_peer is None or tuple(ver) < min_peer:
                    min_peer = tuple(ver)
                try:
                    r = w.call_sync(conn, "dag_channel_open", payload,
                                    timeout=30)
                except protocol.RpcError as e:
                    raise CompileError(
                        f"channel open refused by {s.address}: {e}")
                s.channel_address = r["channel_address"]
                # 1.7-or-older stages omit the field: absent ⇒ unix-only
                s.channel_tcp_address = r.get("channel_tcp_address") or ""
                opened.append(s)
            # pre-dial the trigger sockets to every entry stage (unix
            # on-box, the 1.8 host:port endpoint across nodes)
            for s in self._stages:
                if s.upstream is None:
                    from ray_tpu._private import netx
                    s.trigger = dagch.FrameSocket.dial(netx.pick(
                        s.channel_address, s.channel_tcp_address))
        except CompileError:
            for s in opened:
                self._close_stage(w, s)
            raise
        except Exception as e:  # noqa: BLE001 — any setup failure degrades
            for s in opened:
                self._close_stage(w, s)
            raise CompileError(f"{type(e).__name__}: {e}")
        _REGISTRY[self.dag_id] = weakref.ref(self)
        # trace contexts on trigger/forward frames are 1.6 fields:
        # only send them when EVERY stage peer negotiated >= 1.6 — a
        # legacy stage runs the graph untraced instead of choking on a
        # frame shape it never declared (docs/TRACING.md)
        self._trace_peers = min_peer is not None and min_peer >= (1, 6)
        self._compiled = True

    @staticmethod
    def _negotiate(w, conn, address: str) -> Tuple[int, int]:
        """Version-gate the channel open (the PR-4 pattern: features ride
        the peer's declared minor). A pre-1.5 peer cannot host a dag
        stage — degrade to dynamic instead of failing mid-graph.
        Returns the peer's negotiated version (feature gates above 1.5
        — the 1.6 trace contexts — key off it)."""
        ver = conn.meta.get("peer_protocol_version")
        if ver is None:
            from ray_tpu._private import schema
            try:
                reply = w.call_sync(conn, "__hello__",
                                    schema.hello_payload(), timeout=10)
                ver = tuple(int(v) for v in reply["protocol_version"])
            except protocol.RpcError:
                ver = (1, 0)  # pre-hello peer
            except Exception as e:  # noqa: BLE001
                raise CompileError(f"negotiation with {address} failed: {e}")
            conn.meta["peer_protocol_version"] = ver
        if tuple(ver) < _MIN_PEER_VERSION:
            raise CompileError(
                f"peer {address} negotiated wire schema "
                f"{ver[0]}.{ver[1]} < "
                f"{_MIN_PEER_VERSION[0]}.{_MIN_PEER_VERSION[1]} — "
                "compiled channels need 1.5")
        return tuple(ver)

    @staticmethod
    def _args_template(node: ClassMethodNode) -> List[List[Any]]:
        tpl: List[List[Any]] = []
        for a in node._bound_args:
            if isinstance(a, InputNode):
                tpl.append(["in"])
            elif isinstance(a, DAGNode):
                tpl.append(["up"])
            else:
                tpl.append(["c", serialization.serialize(a).to_bytes()])
        return tpl

    def _close_stage(self, w, s: _Stage):
        # fire-and-forget: this runs on teardown paths that may be ON
        # the io-loop thread (dag_peer_down / dag_stage_error handlers),
        # where a blocking RPC would deadlock the loop; a worker that is
        # already gone tears down implicitly anyway
        try:
            w.try_notify(s.address, "dag_channel_close",
                         {"dag_id": self.dag_id, "stage_id": s.stage_id})
        except Exception:
            pass

    # ------------------------------------------------------------- execute

    def execute(self, input_value: Any = None,
                timeout: Optional[float] = None) -> Any:
        """Run the graph once; returns the output value(s). Transparently
        falls back to the dynamic path on channel failure (the failed
        invocation re-runs dynamically, the next call re-compiles)."""
        timeout = self._timeout_s if timeout is None else timeout
        trig = self._trigger(input_value)
        if trig is None:
            return self._execute_dynamic(input_value)
        dag_id, seq, inv = trig
        inv.event.wait(timeout)
        return self._resolve(dag_id, seq, inv, input_value)

    def execute_async(self, input_value: Any = None,
                      timeout: Optional[float] = None) -> Future:
        """Pipelined trigger: returns a Future completed on the channel
        thread. In-flight invocations are capped at ``ring_slots``
        (slot-reuse safety) — that cap IS the pipeline depth."""
        timeout = self._timeout_s if timeout is None else timeout
        fut: Future = Future()
        trig = self._trigger(input_value)
        if trig is None:
            try:
                fut.set_result(self._execute_dynamic(input_value))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)
            return fut
        dag_id, seq, inv = trig

        def _done():
            # channel thread (deliver/fail): resolve inline; the rare
            # dynamic fallback must not block result delivery for other
            # invocations, so it moves to its own thread
            if inv.failed is not None and inv.error is None:
                def _fb():
                    try:
                        fut.set_result(
                            self._resolve(dag_id, seq, inv, input_value))
                    except BaseException as e:  # noqa: BLE001
                        fut.set_exception(e)
                threading.Thread(target=_fb, daemon=True,
                                 name="rtpu-dag-fallback").start()
                return
            try:
                fut.set_result(self._resolve(dag_id, seq, inv,
                                             input_value))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        _watchdog().arm(inv, timeout)
        inv.set_done_callback(_done)
        return fut

    def _trigger(self, input_value):
        """Send one trigger frame per entry stage; returns
        (dag_id, seq, inv) or None when the graph is running
        dynamic-only."""
        if self._fallback_only or not self._compiled:
            self._maybe_recompile()
        if self._fallback_only or not self._compiled:
            return None
        self._window.acquire()
        with self._lock:
            self._seq += 1
            seq = self._seq
            dag_id = self.dag_id  # pin: a recompile renames mid-flight
        w = global_worker()
        ep = dagch.get_endpoint(w)
        inv = _Invocation(n_outputs=len(self._outputs))
        tc = None
        cur = w._current_trace() if self._trace_peers \
            and tracing.enabled() else None
        if cur is not None and tracing.sampled(cur["trace_id"]):
            # root span of this execution, parented under the caller's
            # current trace (a dag executed inside a task/serve request
            # nests there); stages chain hop spans off the "tc" field.
            # Head-sampled out ⇒ no tc ⇒ stages do zero tracing work.
            inv.trace_span = tracing.Span(
                cur["trace_id"], f"dag.execute:{self.dag_id[:12]}",
                parent_span_id=(None if cur.get("span_id") == "root"
                                else cur.get("span_id")),
                kind="dag.execute", phase="transfer",
                attrs={"dag_id": self.dag_id, "seq": seq})
            tc = inv.trace_span.child_ctx()
        ep.inbox[(dag_id, seq)] = inv
        try:
            blob = serialization.serialize(input_value).to_bytes()
            for s in self._stages:
                if s.upstream is None:
                    frame = {"d": dag_id, "t": s.stage_id,
                             "s": seq, "b": blob}
                    if tc is not None:
                        frame["tc"] = tc
                    s.trigger.send(dagch.DAG_EXEC, frame)
        except Exception as e:  # noqa: BLE001 — send failure = channel down
            inv.fail(f"trigger send failed: {e}")
        return dag_id, seq, inv

    def _resolve(self, dag_id: str, seq: int, inv: _Invocation,
                 input_value) -> Any:
        """Turn a finished (or timed-out) invocation into its value; a
        channel failure re-runs the invocation on the dynamic path —
        each execute() yields exactly one result either way."""
        try:
            w = global_worker()
            ep = getattr(w, "_dag_endpoint", None)
            if ep is not None:
                ep.inbox.pop((dag_id, seq), None)
            if not inv.done:
                inv.fail("execute timed out")  # no-op if just delivered
            if inv.trace_span is not None:
                inv.trace_span.finish(
                    "error" if inv.error is not None
                    or inv.failed is not None else "ok")
            if inv.error is not None:
                raise inv.error
            if inv.failed is not None:
                self._mark_broken(inv.failed)
                return self._execute_dynamic(input_value,
                                             reset_dead=True)
            out = [inv.values[i] for i in range(inv.n_outputs)]
            return out if isinstance(self._root, MultiOutputNode) \
                else out[0]
        finally:
            self._window.release()

    def _note_compile_failure(self):
        import time as _time
        self._compile_fail_at = _time.monotonic()

    _COMPILE_RETRY_S = 1.0

    def _maybe_recompile(self):
        import time as _time
        with self._lock:
            if self._compiled or self._fallback_only:
                return
            if _time.monotonic() - self._compile_fail_at \
                    < self._COMPILE_RETRY_S:
                return  # recent failure: stay dynamic, retry later
            try:
                self._compile()
            except CompileError as e:
                logger.info("dag re-compile failed, staying dynamic "
                            "for now: %s", e)
                self._note_compile_failure()

    def _execute_dynamic(self, input_value, reset_dead: bool = False
                         ) -> Any:
        """The uncompiled path: classic ``.execute()`` + get. Arriving
        here via a channel failure (``reset_dead``), dead cached actors
        are invalidated FIRST so the re-run creates replacements instead
        of submitting to corpses. Each execute() yields exactly one
        result, and the break's DOWNSTREAM stages see the invocation
        exactly once (their compiled copy never fired); stages upstream
        of the break re-run — the same at-least-once contract as task
        retries."""
        from ray_tpu._private.worker import get as _get
        if reset_dead:
            for s in self._stages:
                s.node._class_node._invalidate_if_dead()
        for attempt in (0, 1):
            res = self._root.execute(input_value)
            refs = res if isinstance(res, list) else [res]
            try:
                vals = _get(refs, timeout=max(self._timeout_s, 60.0))
            except (exc.ActorDiedError, exc.ActorUnavailableError,
                    exc.ActorError) as e:
                # raced a death mid-re-run: invalidate and retry once.
                # A death downstream surfaces WRAPPED (the sink fails
                # resolving its upstream arg and reports an ActorError),
                # so match the message for the wrapped forms too.
                died = not isinstance(e, exc.ActorError) or \
                    "ActorDiedError" in str(e) or \
                    "ActorUnavailableError" in str(e)
                if attempt or not died:
                    raise
                for s in self._stages:
                    s.node._class_node._invalidate_if_dead()
                continue
            return vals if isinstance(self._root, MultiOutputNode) \
                else vals[0]

    # -------------------------------------------------------- failure path

    def _on_channel_failure(self, reason: str, seq: Optional[int] = None):
        """A peer died or a stage forward broke (raylet dag_peer_down /
        stage dag_stage_error notify, routed via worker.py)."""
        self._mark_broken(reason)
        w = global_worker()
        ep = getattr(w, "_dag_endpoint", None)
        if ep is None:
            return
        for (did, s), inv in list(ep.inbox.items()):
            if did == self.dag_id and (seq is None or s == seq):
                inv.fail(reason)

    def _mark_broken(self, reason: str):
        with self._lock:
            if not self._compiled:
                return
            self._compiled = False
        logger.warning("compiled dag %s torn down (%s); falling back to "
                       "dynamic dispatch, will re-compile on next call",
                       self.dag_id, reason)
        self._teardown_channels()

    def _teardown_channels(self):
        _REGISTRY.pop(self.dag_id, None)
        w = None
        try:
            w = global_worker()
        except RuntimeError:
            pass
        for s in self._stages:
            if s.trigger is not None:
                s.trigger.close()
                s.trigger = None
            if w is not None and s.channel_address is not None:
                self._close_stage(w, s)
            s.channel_address = None

    def teardown(self):
        """Release channels, rings, and sockets. The graph object stays
        usable — the next execute() re-compiles."""
        with self._lock:
            self._compiled = False
        self._teardown_channels()

    def __del__(self):
        try:
            for s in self._stages:
                if s.trigger is not None:
                    s.trigger.close()
            _REGISTRY.pop(self.dag_id, None)
        except Exception:
            pass
