"""DAG node types.

Reference analogue: python/ray/dag/dag_node.py, function_node.py,
class_node.py, input_node.py. ``fn.bind(x)`` builds a lazy node;
``node.execute(input)`` walks the DAG, submitting tasks/actor calls and
wiring ObjectRefs as dependencies (the scheduler overlaps anything
independent).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """Base lazy node. Subclasses implement ``_execute_impl``."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal --

    def _children(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(
                self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def _resolve_args(self, cache: Dict[int, Any], input_value: Any
                      ) -> Tuple[tuple, dict]:
        def res(v):
            if isinstance(v, DAGNode):
                return v._execute_cached(cache, input_value)
            return v
        args = tuple(res(a) for a in self._bound_args)
        kwargs = {k: res(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_cached(self, cache: Dict[int, Any], input_value: Any):
        key = id(self)
        if key not in cache:
            cache[key] = self._execute_impl(cache, input_value)
        return cache[key]

    def _execute_impl(self, cache, input_value):
        raise NotImplementedError

    def execute(self, input_value: Any = None):
        """Run the DAG rooted here; returns an ObjectRef (or value for
        InputNode roots)."""
        return self._execute_cached({}, input_value)

    def compile(self, **options) -> "CompiledDAG":
        """Pre-wire this graph into a :class:`CompiledDAG`: actors
        created once, peer-to-peer channels opened between consecutive
        stages, one trigger frame per execution (docs/COMPILED_DAGS.md).
        Graphs that cannot compile (non-actor stages, multi-upstream
        nodes, pre-1.5 peers) transparently run the dynamic path."""
        from ray_tpu.dag.compiled_dag import CompiledDAG
        return CompiledDAG(self, **options)

    # reference-parity alias (python/ray/dag experimental_compile)
    experimental_compile = compile


class InputNode(DAGNode):
    """Placeholder for the runtime input (reference: input_node.py:343).
    Usable as a context manager for parity with the reference API."""

    def __init__(self):
        super().__init__((), {})

    def _execute_impl(self, cache, input_value):
        return input_value

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    """Lazy invocation of a remote function."""

    def __init__(self, remote_fn, args, kwargs, opts=None):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn
        self._opts = opts or {}

    def _execute_impl(self, cache, input_value):
        args, kwargs = self._resolve_args(cache, input_value)
        return self._remote_fn._remote(args, kwargs, self._opts)


class ClassNode(DAGNode):
    """Lazy actor instantiation; attribute access yields method nodes.

    The actor handle is cached ON THE NODE across executions — a DAG
    instance owns one actor per ClassNode (the reference's class_node
    semantics), so repeated ``dag.execute()`` calls reuse the same actor
    instead of leaking a fresh one per run. Constructor args are
    resolved on the first execution only."""

    def __init__(self, actor_cls, args, kwargs, opts=None):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._opts = opts or {}
        self._cached_actor = None

    def _execute_impl(self, cache, input_value):
        if self._cached_actor is None:
            args, kwargs = self._resolve_args(cache, input_value)
            self._cached_actor = self._actor_cls._create(
                self._opts, args, kwargs)
        return self._cached_actor

    def _invalidate_actor(self):
        """Drop the cached handle; the next execution creates a fresh
        actor (used when the actor died — compiled-DAG fallback)."""
        self._cached_actor = None

    def _invalidate_if_dead(self):
        if self._cached_actor is None:
            return
        try:
            from ray_tpu._private.worker import global_worker
            w = global_worker()
            info = w.call_sync(w.gcs, "get_actor",
                               {"actor_id": self._cached_actor._id_hex},
                               timeout=10)
            if info.get("error") or info.get("state") == "DEAD":
                self._cached_actor = None
        except Exception:
            self._cached_actor = None

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodStub(self, name)


class _ClassMethodStub:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method_name,
                               args, kwargs)


class ClassMethodNode(DAGNode):
    """Lazy method call on a ClassNode-created actor."""

    def __init__(self, class_node: ClassNode, method_name: str,
                 args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method_name = method_name

    def _children(self):
        return super()._children() + [self._class_node]

    def _execute_impl(self, cache, input_value):
        actor = self._class_node._execute_cached(cache, input_value)
        args, kwargs = self._resolve_args(cache, input_value)
        return getattr(actor, self._method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Aggregates several output nodes so one graph can fan out to
    multiple sinks (reference: python/ray/dag MultiOutputNode).
    ``execute()`` returns the outputs as a list (of ObjectRefs on the
    dynamic path; of values when compiled)."""

    def __init__(self, outputs):
        super().__init__(tuple(outputs), {})

    def _execute_impl(self, cache, input_value):
        return [a._execute_cached(cache, input_value)
                if isinstance(a, DAGNode) else a
                for a in self._bound_args]
