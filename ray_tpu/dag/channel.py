"""Pre-wired peer-to-peer channels for compiled actor DAGs.

The dynamic ``.execute()`` path pays the full dispatch pipeline per hop:
owner bookkeeping, two thread handoffs inside each worker
(io-loop → exec thread → io-loop), and a driver round trip between
stages — PERF.md puts the residual at ~420 µs/hop. For a *static* graph
all of that is re-derivable, so ``dag.compile()`` pays it once:

* every process (driver and stage workers) opens ONE dag listener — a
  plain blocking unix socket served by ordinary threads, deliberately
  outside the asyncio control plane;
* compile-time ``dag_channel_open`` RPCs (control plane, schema 1.5)
  hand each stage its spec and the downstream channel addresses; the
  stage dials its peers once and keeps the sockets;
* an invocation is a single ``dag_exec`` trigger frame; each stage's
  channel thread does recv → run the actor method inline → forward to
  the downstream peer socket. No owner, no raylet, no lease, no event
  loop on the forward path;
* payloads above the inline threshold ride reusable plasmax ring slots
  (``PlasmaxStore.ring_*``: seal/unseal cycling, zero allocator churn)
  when writer and reader share the segment, else inline bytes.

Frames reuse the protocol.py msgpack framing (``[NOTIFY, nil, method,
payload]``) so a channel is wire-inspectable with the same tooling —
see docs/WIRE_PROTOCOL.md §1.5 for the frame schemas and
docs/COMPILED_DAGS.md for the execution model.

Reference analogue: accelerated/compiled DAG execution in the reference
(python/ray/dag compiled graphs over shared-memory channels); the
channel-over-socket design here matches this runtime's plasmax +
msgpack substrate instead of the reference's mutable-plasma channels.
"""

from __future__ import annotations

import hashlib
import logging
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private import chaos, netx, protocol, serialization, tracing
from ray_tpu.common.ids import ObjectID

logger = logging.getLogger(__name__)

# dag-channel frame methods (declared in schema.py; these flow over the
# dedicated channel sockets, not the control-plane Server)
DAG_EXEC = "dag_exec"          # trigger / stage→stage forward
DAG_RESULT = "dag_result"      # terminal stage → driver


def pack_dag_frame(method: str, payload: Dict[str, Any]) -> bytes:
    return protocol.pack_frame([protocol.NOTIFY, None, method, payload])


class ChannelClosed(ConnectionError):
    pass


class FrameSocket:
    """A persistent blocking channel socket with the msgpack framing.

    Send is locked (stages can fan out to one peer from several threads);
    recv is single-reader (each accepted connection gets one thread).
    Chaos site ``dag.channel`` fires here on both directions.
    """

    def __init__(self, sock: socket.socket, peer: str = ""):
        self._sock = sock
        self._lock = threading.Lock()
        self._closed = False
        self.peer = peer
        self.peer_host = netx.host_of(peer)  # '' for unix/accepted conns

    @classmethod
    def dial(cls, address: str) -> "FrameSocket":
        """Dial a channel endpoint: ``unix:<path>`` on-box,
        ``host:port`` (1.8) across nodes."""
        if address.startswith("unix:"):
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(address[5:])
            return cls(s, peer=address)
        host, sep, port = address.rpartition(":")
        if not sep:
            raise ChannelClosed(f"bad dag channel address: {address}")
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.connect((host, int(port)))
        except OSError as e:
            s.close()
            raise ChannelClosed(str(e)) from e
        return cls(s, peer=address)

    def send(self, method: str, payload: Dict[str, Any]):
        if self.peer_host and netx.partitioned(self.peer_host):
            # one-direction sever: the frame is lost AND the socket dies
            # (an unplugged cable) — the stage reports over the control
            # plane and the driver falls back to dynamic dispatch
            self.close()
            raise ChannelClosed("chaos: network partition")
        act = chaos.hit("dag.channel", method)
        if act is not None:
            op = act["op"]
            if op == "drop":
                return
            if op == "delay":
                import time as _time
                _time.sleep(float(act.get("delay_s", 0.05)))
            elif op == "reset":
                self.close()
                raise ChannelClosed("chaos: dag channel reset (send)")
        data = pack_dag_frame(method, payload)
        with self._lock:
            if self._closed:
                raise ChannelClosed("channel closed")
            try:
                self._sock.sendall(data)
            except OSError as e:
                self._closed = True
                raise ChannelClosed(str(e)) from e

    def recv(self):
        """Blocking read of one [mtype, seq, method, payload] frame."""
        try:
            frame = protocol.read_frame_sync(self._sock)
        except (OSError, ConnectionError) as e:
            raise ChannelClosed(str(e)) from e
        act = chaos.hit("dag.channel", frame[2])
        if act is not None:
            op = act["op"]
            if op == "drop":
                return None  # caller loops
            if op == "delay":
                import time as _time
                _time.sleep(float(act.get("delay_s", 0.05)))
            elif op == "reset":
                self.close()
                raise ChannelClosed("chaos: dag channel reset (recv)")
        return frame

    def close(self):
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class DagListener:
    """Per-process dag channel endpoint: one listening unix socket, an
    accept thread, and one reader thread per accepted connection. The
    handler runs ON the reader thread — that thread *is* the stage
    executor on workers (recv → exec → forward with no handoff)."""

    def __init__(self, path: str,
                 handler: Callable[[str, Dict[str, Any]], None],
                 tcp_host: Optional[str] = None):
        self.path = path
        self.address = f"unix:{path}"
        self.handler = handler
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(path)
        except OSError:
            pass
        self._sock.bind(path)
        self._sock.listen(64)
        self._closed = False
        self._conns: List[FrameSocket] = []
        # 1.8: host:port twin of the endpoint — same frames, same reader
        # threads, so a stage on another node forwards identically
        self.tcp_address = ""
        self._tcp_sock: Optional[socket.socket] = None
        if tcp_host:
            try:
                ts = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                ts.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                ts.bind((tcp_host, 0))
                ts.listen(64)
                self._tcp_sock = ts
                self.tcp_address = f"{tcp_host}:{ts.getsockname()[1]}"
            except OSError:
                logger.warning("dag listener: TCP endpoint on %s failed; "
                               "channels stay unix-only", tcp_host)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, args=(self._sock,),
            name="rtpu-dag-accept", daemon=True)
        self._accept_thread.start()
        if self._tcp_sock is not None:
            threading.Thread(
                target=self._accept_loop, args=(self._tcp_sock,),
                name="rtpu-dag-accept-tcp", daemon=True).start()

    def _accept_loop(self, lsock: socket.socket):
        while not self._closed:
            try:
                conn, _ = lsock.accept()
            except OSError:
                return
            if conn.family == socket.AF_INET:
                try:
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                except OSError:
                    pass
            fs = FrameSocket(conn)
            self._conns.append(fs)
            threading.Thread(target=self._reader_loop, args=(fs,),
                             name="rtpu-dag-chan", daemon=True).start()

    def _reader_loop(self, fs: FrameSocket):
        while not self._closed:
            try:
                frame = fs.recv()
            except ChannelClosed:
                return
            if frame is None:
                continue  # chaos drop
            try:
                self.handler(frame[2], frame[3])
            except Exception:
                logger.exception("dag channel handler failed")

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        if self._tcp_sock is not None:
            try:
                self._tcp_sock.close()
            except OSError:
                pass
        for fs in self._conns:
            fs.close()
        try:
            os.unlink(self.path)
        except OSError:
            pass


# --------------------------------------------------------------------------
# payload encoding: inline bytes vs plasmax ring slot


def ring_slot_id(dag_id: str, stage_id: int, slot: int) -> ObjectID:
    digest = hashlib.sha256(
        f"dagring:{dag_id}:{stage_id}:{slot}".encode()).digest()
    return ObjectID(digest[:ObjectID.SIZE])


class BufferRing:
    """The writer side of one stage's output ring: N fixed-size plasmax
    slots cycled seal→unseal→refill→seal (see PlasmaxStore.ring_*).
    Slots are created lazily on the first payload that exceeds the
    inline threshold and freed at teardown."""

    def __init__(self, plasma, dag_id: str, stage_id: int,
                 nslots: int = 2, slot_bytes: int = 1 << 20):
        self.plasma = plasma
        self.dag_id = dag_id
        self.stage_id = stage_id
        self.nslots = max(1, int(nslots))
        self.slot_bytes = int(slot_bytes)
        self._created: Dict[int, ObjectID] = {}
        self._seq = 0

    def write(self, ser) -> Optional[Dict[str, Any]]:
        """Write a SerializedObject into the next slot; returns the frame
        descriptor {"o": hex, "n": size} or None (caller sends inline)."""
        size = ser.total_size
        if size > self.slot_bytes:
            return None
        slot = self._seq % self.nslots
        self._seq += 1
        oid = self._created.get(slot)
        try:
            if oid is None:
                oid = ring_slot_id(self.dag_id, self.stage_id, slot)
                buf = self.plasma.ring_create(oid, self.slot_bytes)
                self._created[slot] = oid
            else:
                buf = self.plasma.ring_recycle(oid)
                if buf is None:
                    return None  # reader wedged/evicted: inline this one
                buf = buf[:self.slot_bytes]
        except Exception:
            return None  # store pressure etc.: inline is always correct
        ser.write_into(buf[:size])
        buf.release()
        self.plasma.ring_seal(oid)
        return {"o": oid.hex(), "n": size}

    def free(self):
        for oid in self._created.values():
            try:
                self.plasma.ring_free(oid)
            except Exception:
                pass
        self._created.clear()


def encode_value(ser, ring: Optional[BufferRing],
                 inline_max: int) -> Dict[str, Any]:
    """Frame fields for one serialized payload: ring slot when it pays,
    inline bytes otherwise."""
    if ring is not None and ser.total_size > inline_max:
        desc = ring.write(ser)
        if desc is not None:
            return desc
    return {"b": ser.to_bytes()}


def decode_value(plasma, payload: Dict[str, Any]) -> Any:
    """Decode a dag frame payload into a Python value. Ring-slot reads
    copy out of shared memory before deserializing so the slot can be
    recycled immediately (one copy — the price of reuse; zero-copy
    views would pin the slot across invocations).

    Error envelopes re-raise here (serialization.deserialize contract),
    so callers see stage application errors as exceptions."""
    if payload.get("o") is not None:
        oid = ObjectID.from_hex(payload["o"])
        buf = plasma.get_buffer(oid)
        if buf is None:
            raise ChannelClosed(f"ring slot {payload['o'][:12]} vanished")
        try:
            data = bytes(buf[:payload["n"]])
        finally:
            buf.release()
            plasma.release(oid)
        return serialization.deserialize(data)
    return serialization.deserialize(payload["b"])


# --------------------------------------------------------------------------
# worker-side stage runtime


class StageRuntime:
    """One compiled stage living in an actor worker: the bound method,
    the arg template, and the pre-dialed downstream channel sockets.

    ``run()`` is invoked on the dag reader thread with the upstream
    value; it executes the actor method INLINE (bypassing the
    io-loop→exec-thread→io-loop round trip the dynamic actor_call path
    pays) and pushes the result straight to the downstream sockets.
    """

    def __init__(self, worker, payload: Dict[str, Any]):
        self.worker = worker
        self.dag_id = payload["dag_id"]
        self.stage_id = int(payload["stage_id"])
        self.owner = payload["owner_address"]
        inst = worker._actor_instance
        if inst is None:
            raise protocol.RpcError("dag_channel_open: not an actor worker")
        self.method = getattr(inst, payload["method"], None)
        if self.method is None:
            raise protocol.RpcError(
                f"{type(inst).__name__} has no method {payload['method']}")
        # arg template: [["in"], ["up"], ["c", <serialized bytes>]] per
        # positional arg; kwargs are constants only
        self.args_tpl = [
            (t[0], serialization.deserialize(t[1]) if t[0] == "c" else None)
            for t in payload["args_tpl"]]
        self.kwargs = {k: serialization.deserialize(v)
                       for k, v in (payload.get("kwargs_tpl") or {}).items()}
        ring_cfg = payload.get("ring") or {}
        self.ring = BufferRing(
            worker.plasma, self.dag_id, self.stage_id,
            nslots=int(ring_cfg.get("slots", 2)),
            slot_bytes=int(ring_cfg.get("slot_bytes", 1 << 20)))
        self.inline_max = worker.config.max_inline_object_size
        # downstream peers: [{"stage_id", "address", "tcp_address",
        # "sink", "index"}] — dial now, keep forever (sink = the
        # driver's result endpoint); unix on-box, host:port off-box
        self.downstream: List[Dict[str, Any]] = []
        for peer in payload["downstream"]:
            addr = netx.pick(peer.get("address"), peer.get("tcp_address"))
            fs = FrameSocket.dial(addr)
            self.downstream.append({"sock": fs, "sink": peer.get("sink"),
                                    "stage_id": int(peer.get("stage_id",
                                                             -1)),
                                    "index": int(peer.get("index", 0))})

    # -- forward path (dag reader thread) --

    def run(self, seq: int, payload: Dict[str, Any]):
        if chaos._ENGINE is not None:
            # chaos injection point: targeted stage faults — the method
            # filter is the stage id, so a schedule can SIGKILL exactly
            # the N-th execution of one mid-graph stage (the generic
            # dag.channel site can't tell stages apart)
            chaos.hit("dag.stage", str(self.stage_id))
        # hop span (1.6): frames from a >=1.6 driver carry "tc"; this
        # stage's span chains under the upstream hop (or the execute
        # root) and its own ctx rides the forwarded frame — the trace
        # tree follows the data through the pipe. Legacy frames have no
        # "tc" and the graph runs untraced.
        tc = payload.get("tc")
        span = None
        if tc and tracing.enabled():
            span = tracing.Span(
                tc["trace_id"],
                f"dag.stage:{self.method.__name__}",
                parent_span_id=tc.get("span_id"), kind="dag.hop",
                phase="execute",
                attrs={"dag_id": self.dag_id,
                       "stage_id": self.stage_id, "seq": seq})
        fwd_tc = span.child_ctx() if span is not None else None
        try:
            value = decode_value(self.worker.plasma, payload)
        except BaseException as e:  # noqa: BLE001 — upstream app error
            # an upstream stage error travels the pipe as an error
            # envelope; terminal stages surface it to the driver, middle
            # stages just pass it on without running user code
            self._forward_error(seq, e, tc=fwd_tc)
            if span is not None:
                span.finish("error")
            return
        args = [value if t[0] in ("in", "up") else t[1]
                for t in self.args_tpl]
        prev_trace = None
        if span is not None:
            # nested submits from stage user code parent under this hop
            prev_trace = getattr(self.worker.task_context, "trace", None)
            self.worker.task_context.trace = span.trace_ctx()
        try:
            result = self.method(*args, **self.kwargs)
        except BaseException as e:  # noqa: BLE001 — user code
            from ray_tpu import exceptions as exc
            err = exc.ActorError.capture(
                f"{type(self.worker._actor_instance).__name__}."
                f"{self.method.__name__}", e)
            self._forward_error(seq, err, tc=fwd_tc)
            if span is not None:
                span.finish("error")
            return
        finally:
            if span is not None:
                self.worker.task_context.trace = prev_trace
        ser = serialization.serialize(result)
        desc = encode_value(ser, self.ring, self.inline_max)
        t_fwd = time.time()
        self._forward(seq, desc, app_error=False, tc=fwd_tc)
        if span is not None:
            end = time.time()
            if end - t_fwd > 1e-4:
                tracing.record_span(
                    span.trace_id, tracing.new_span_id(),
                    f"dag.forward:{self.stage_id}",
                    parent_span_id=span.span_id, kind="dag.hop",
                    phase="transfer", start_ts=t_fwd, end_ts=end)
            span.finish(end_ts=end)

    def _forward_error(self, seq: int, e: BaseException,
                       tc: Optional[Dict[str, str]] = None):
        ser = serialization.serialize_error(e)
        self._forward(seq, {"b": ser.to_bytes()}, app_error=True, tc=tc)

    def _forward(self, seq: int, desc: Dict[str, Any], app_error: bool,
                 tc: Optional[Dict[str, str]] = None):
        for peer in self.downstream:
            frame = {"d": self.dag_id, "s": seq, **desc}
            if tc is not None:
                frame["tc"] = tc
            try:
                if peer["sink"]:
                    peer["sock"].send(DAG_RESULT,
                                      {**frame, "i": peer["index"],
                                       "ae": app_error})
                else:
                    peer["sock"].send(DAG_EXEC,
                                      {**frame, "t": peer["stage_id"]})
            except ChannelClosed as e:
                # downstream died: tell the driver over the CONTROL plane
                # (this channel may have no direct driver socket) so it
                # can fall back without waiting out its exec timeout
                self._notify_driver_error(seq, str(e))

    def _notify_driver_error(self, seq: int, reason: str):
        self.worker.try_notify(self.owner, "dag_stage_error",
                               {"dag_id": self.dag_id,
                                "stage_id": self.stage_id,
                                "seq": seq, "reason": reason})

    def close(self):
        for peer in self.downstream:
            peer["sock"].close()
        self.ring.free()


# --------------------------------------------------------------------------
# per-process endpoint wiring (driver and workers share this)


_ENDPOINT_LOCK = threading.Lock()


def get_endpoint(worker) -> "DagEndpoint":
    ep = getattr(worker, "_dag_endpoint", None)
    if ep is None:
        with _ENDPOINT_LOCK:
            ep = getattr(worker, "_dag_endpoint", None)
            if ep is None:
                ep = DagEndpoint(worker)
                worker._dag_endpoint = ep
    return ep


class DagEndpoint:
    """Everything dag-channel in one process: the listener, the stage
    registry (workers), and the driver inbox (compiling processes)."""

    def __init__(self, worker):
        self.worker = worker
        path = os.path.join(
            worker.session_dir or "/tmp",
            f"dagch_{worker.worker_id.hex()[:12]}.sock")
        self.listener = DagListener(
            path, self._on_frame,
            tcp_host=netx.node_ip() if netx.enabled() else None)
        self.address = self.listener.address
        self.tcp_address = self.listener.tcp_address
        self.stages: Dict[tuple, StageRuntime] = {}
        # driver side: (dag_id, seq) -> _Invocation
        self.inbox: Dict[tuple, Any] = {}
        self._lock = threading.Lock()

    # channel-thread entry: trigger/forward frames run the stage right
    # here; result frames complete driver invocations
    def _on_frame(self, method: str, payload: Dict[str, Any]):
        if method == DAG_EXEC:
            stage = self.stages.get((payload["d"], int(payload["t"])))
            if stage is None:
                logger.warning("dag_exec for unknown stage %s/%s",
                               payload.get("d"), payload.get("t"))
                return
            stage.run(payload["s"], payload)
        elif method == DAG_RESULT:
            inv = self.inbox.get((payload["d"], payload["s"]))
            if inv is not None:
                inv.deliver(int(payload.get("i", 0)), payload,
                            self.worker.plasma)

    # -- worker side --

    def open_stage(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        rt = StageRuntime(self.worker, payload)
        key = (rt.dag_id, rt.stage_id)
        with self._lock:
            old = self.stages.pop(key, None)
            self.stages[key] = rt
        if old is not None:
            old.close()
        return {"channel_address": self.address,
                "channel_tcp_address": self.tcp_address}

    def close_stage(self, dag_id: str, stage_id: Optional[int] = None):
        with self._lock:
            keys = [k for k in self.stages
                    if k[0] == dag_id
                    and (stage_id is None or k[1] == stage_id)]
            rts = [self.stages.pop(k) for k in keys]
        for rt in rts:
            rt.close()

    def close(self):
        with self._lock:
            stages = {id(s): s for s in self.stages.values()}
            self.stages.clear()
        for s in stages.values():
            s.close()
        self.listener.close()
