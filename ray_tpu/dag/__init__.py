"""Lazy task/actor DAG authoring — .bind()/.execute()/.compile().

Reference analogue: python/ray/dag (DAGNode dag_node.py:339,
FunctionNode/ClassNode/InputNode/MultiOutputNode). DAGs built here are
the substrate the workflow engine executes durably; actor-method graphs
additionally compile into pre-wired peer-to-peer channel pipelines
(compiled_dag.py, docs/COMPILED_DAGS.md) that skip the per-call
control-plane dispatch entirely.
"""

from ray_tpu.dag.dag_node import (ClassMethodNode, ClassNode, DAGNode,
                                  FunctionNode, InputNode,
                                  MultiOutputNode)


def __getattr__(name):
    # CompiledDAG imports the worker runtime; keep dag authoring
    # importable without dragging the full runtime in
    if name in ("CompiledDAG", "CompileError"):
        from ray_tpu.dag import compiled_dag
        return getattr(compiled_dag, name)
    raise AttributeError(name)


__all__ = ["DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
           "InputNode", "MultiOutputNode", "CompiledDAG", "CompileError"]
