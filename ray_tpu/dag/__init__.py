"""Lazy task/actor DAG authoring — .bind()/.execute().

Reference analogue: python/ray/dag (DAGNode dag_node.py:339,
FunctionNode/ClassNode/InputNode). DAGs built here are the substrate
the workflow engine executes durably.
"""

from ray_tpu.dag.dag_node import (ClassMethodNode, ClassNode, DAGNode,
                                  FunctionNode, InputNode)

__all__ = ["DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
           "InputNode"]
