"""ray_tpu — a TPU-native distributed AI compute framework.

Public API parity with the reference (python/ray/__init__.py): init/shutdown,
remote, get/put/wait, kill/cancel, actors, placement groups, cluster state —
plus the TPU-first additions (get_tpu_ids, tpu topology resources, the
``parallel`` mesh/sharding layer).
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu._version import __version__
from ray_tpu.common.config import SystemConfig, global_config
from ray_tpu.common.ids import JobID, NodeID, ObjectID, TaskID
from ray_tpu.common.options import validate_options
from ray_tpu import exceptions
from ray_tpu._private import worker as _worker_mod
from ray_tpu._private.worker import ObjectRef, Worker, MODE_DRIVER
from ray_tpu._private import node as _node_mod
from ray_tpu.actor import (ActorClass, ActorHandle, get_actor, kill as _kill)
from ray_tpu.remote_function import RemoteFunction

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "ObjectRef", "ActorHandle",
    "cluster_resources", "available_resources", "nodes", "get_tpu_ids",
    "get_gpu_ids", "get_runtime_context", "method", "exceptions",
    "__version__",
]

_init_lock = threading.Lock()
_node_processes: Optional[_node_mod.NodeProcesses] = None
_storage_env_set = False  # init(storage=...) set RTPU_STORAGE this run


def _client():
    """Active ray:// client connection, or None (reference:
    util/client_connect.py client-mode hooks)."""
    from ray_tpu.util.client import worker as _cw
    c = _cw._client
    return c if (c is not None and c.connected) else None


def is_initialized() -> bool:
    if _client() is not None:
        return True
    w = _worker_mod._global_worker
    return w is not None and w.connected


def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         num_gpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         labels: Optional[Dict[str, str]] = None,
         object_store_memory: Optional[int] = None,
         namespace: str = "",
         storage: Optional[str] = None,
         ignore_reinit_error: bool = False,
         _system_config: Optional[Dict[str, Any]] = None,
         log_to_driver: bool = True) -> Dict[str, Any]:
    """Start a local cluster (head) or connect to an existing one.

    Reference analogue: ray.init (python/ray/_private/worker.py:1031).
    """
    global _node_processes, _storage_env_set
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                c = _client()
                if c is not None:
                    return dict(c.server_info)
                return _worker_mod._global_worker.runtime_context
            raise RuntimeError("ray_tpu.init() called twice "
                               "(use ignore_reinit_error=True)")
        config = SystemConfig().apply_env_overrides()
        if _system_config:
            config.update(_system_config)
        if address is None:
            address = os.environ.get("RTPU_ADDRESS")
        if address and address.startswith("ray://"):
            # remote driver: everything routes over the client protocol
            # (reference: ray.init("ray://...") → util/client_connect.py)
            from ray_tpu.util.client import worker as _cw
            c = _cw.connect(address[len("ray://"):], namespace=namespace)
            if storage is not None:
                os.environ["RTPU_STORAGE"] = storage
                _storage_env_set = True
                c._call("client_kv", {"op": "put", "key": "@storage/root",
                                      "value": storage.encode()})
            return {"address": address, "namespace": namespace,
                    **{k: v for k, v in c.server_info.items()}}
        res: Dict[str, float] = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        if num_tpus is not None:
            res["TPU"] = float(num_tpus)
        if num_gpus is not None:
            res["GPU"] = float(num_gpus)

        if storage is not None:
            # cluster-wide storage root (reference: ray.init(storage=) →
            # _private/storage.py): workflows and any component needing
            # durable shared storage resolve it from here
            os.environ["RTPU_STORAGE"] = storage
            _storage_env_set = True

        w = Worker()
        w.log_to_driver = log_to_driver
        if address is None:
            procs = _node_mod.start_head(
                config, resources=res, labels=labels,
                object_store_memory=object_store_memory)
            _node_processes = procs
            w.connect(MODE_DRIVER, procs.gcs_address, procs.raylet_address,
                      procs.store_path, procs.node_id, procs.session_dir,
                      namespace=namespace)
        else:
            # connect to an existing cluster: find a raylet on this host
            import json as _json
            from ray_tpu._private import protocol as _protocol
            io = _protocol.EventLoopThread("probe")
            conn = io.run(_protocol.connect(address))
            nodes_ = io.run(conn.call("get_nodes", {}))
            conn.close()
            io.stop()
            hostname = os.uname().nodename
            candidates = [n for n in nodes_ if n["alive"]]
            local = [n for n in candidates if n.get("hostname") == hostname
                     and os.path.exists(n["object_store_path"])]
            target = (local or candidates)[0]
            session_dir = os.environ.get(
                "RTPU_SESSION_DIR", _node_mod.new_session_dir())
            w.connect(MODE_DRIVER, address,
                      target["raylet_address"].replace("127.0.0.1:", "")
                      if False else _raylet_unix_for(target, session_dir),
                      target["object_store_path"], target["node_id"],
                      session_dir, namespace=namespace)
        if storage is not None:
            from ray_tpu._private.storage import _publish
            _publish(storage)
        w.config = config
        w.runtime_context = {
            "gcs_address": w.gcs and address or
            (_node_processes.gcs_address if _node_processes else address),
            "session_dir": w.session_dir,
            "node_id": w.node_id,
            "job_id": w.job_id.hex(),
            "namespace": namespace,
        }
        from ray_tpu._private import usage as _usage
        _usage.write_report(w.session_dir,
                            {"node_id": w.node_id,
                             "namespace": namespace})
        atexit.register(shutdown)
        return w.runtime_context


def _raylet_unix_for(node_info: Dict[str, Any], session_dir: str) -> str:
    # Raylets listen on both a unix socket (intra-node) and TCP (inter-node).
    # When connecting by address we use TCP unless a local socket exists.
    sock = os.path.join(os.path.dirname(node_info["object_store_path"]),
                        f"raylet_{node_info['node_id'][:12]}.sock")
    if os.path.exists(sock):
        return f"unix:{sock}"
    return node_info["raylet_address"]


def shutdown():
    global _node_processes, _storage_env_set
    if _storage_env_set:
        # don't leak this run's storage root into the next init
        os.environ.pop("RTPU_STORAGE", None)
        _storage_env_set = False
    if _client() is not None:
        from ray_tpu.util.client import worker as _cw
        _cw.disconnect()
        return
    w = _worker_mod._global_worker
    if w is not None and w.connected:
        w.disconnect()
    _worker_mod._global_worker = None
    # drop any driver-side chaos engine so one chaos run cannot leak
    # faults into the next init in the same process
    from ray_tpu._private import chaos as _chaos
    _chaos.clear()
    if _node_processes is not None:
        _node_processes.kill_all()
        _node_processes = None


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_tpus=1, ...)`` for functions and classes."""
    if len(args) == 1 and not kwargs and (callable(args[0])):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target, {})
        return RemoteFunction(target, {})
    opts = kwargs

    def decorator(target):
        if isinstance(target, type):
            return ActorClass(target, opts)
        return RemoteFunction(target, opts)
    return decorator


def method(**opts):
    """Per-method options decorator (parity: ray.method)."""
    def decorator(m):
        m.__rtpu_method_opts__ = opts
        return m
    return decorator


def put(value: Any) -> ObjectRef:
    c = _client()
    if c is not None:
        return c.put(value)
    return _worker_mod.global_worker().put_object(value)


def get(refs: Union[ObjectRef, List[ObjectRef]], *,
        timeout: Optional[float] = None):
    c = _client()
    if c is not None:
        if isinstance(refs, list):
            return c.get(refs, timeout=timeout)
        return c.get([refs], timeout=timeout)[0]
    return _worker_mod.get(refs, timeout=timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    refs = list(refs)
    if not refs:
        return [], []
    if num_returns > len(refs):
        raise ValueError("num_returns > len(refs)")
    c = _client()
    if c is not None:
        return c.wait(refs, num_returns, timeout)
    return _worker_mod.global_worker().wait(refs, num_returns, timeout)


def kill(actor, *, no_restart: bool = True):
    c = _client()
    if c is not None:
        c.kill_actor(actor._id_hex, no_restart=no_restart)
        return
    _kill(actor, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    c = _client()
    if c is not None:
        c.cancel(ref.hex(), force=force)
        return
    w = _worker_mod.global_worker()
    task_id = ref.id().task_id().hex()
    # leased/parked tasks are invisible to the raylet (direct
    # owner->worker pushes) — cancel them owner-side first
    w.cancel_leased_task(task_id)
    w.call_sync(w.raylet, "cancel_task",
                {"task_id": task_id, "force": force})


def cluster_resources() -> Dict[str, float]:
    c = _client()
    if c is not None:
        return c.cluster_info("cluster_resources")
    w = _worker_mod.global_worker()
    return w.call_sync(w.gcs, "cluster_resources", {})


def available_resources() -> Dict[str, float]:
    c = _client()
    if c is not None:
        return c.cluster_info("available_resources")
    w = _worker_mod.global_worker()
    return w.call_sync(w.gcs, "available_resources", {})


def nodes() -> List[Dict[str, Any]]:
    c = _client()
    if c is not None:
        return c.cluster_info("nodes")
    w = _worker_mod.global_worker()
    return w.call_sync(w.gcs, "get_nodes", {})


def get_tpu_ids() -> List[int]:
    """TPU chip IDs granted to the current task/actor (the analogue of the
    reference's get_gpu_ids, worker.py:821; chips surface to JAX via
    TPU_VISIBLE_CHIPS)."""
    w = _worker_mod.global_worker()
    return list(w.tpu_chips)


def get_gpu_ids() -> List[int]:
    return []


class _RuntimeContext:
    @property
    def worker(self):
        return _worker_mod.global_worker()

    def get_node_id(self) -> str:
        return self.worker.node_id

    def get_job_id(self) -> str:
        return self.worker.job_id.hex()

    def get_task_id(self) -> Optional[str]:
        t = self.worker.current_task_id
        return t.hex() if t else None

    def get_actor_id(self) -> Optional[str]:
        a = self.worker.current_actor_id
        return a.hex() if a else None

    def get_worker_id(self) -> str:
        return self.worker.worker_id.hex()

    def get_assigned_resources(self) -> Dict[str, float]:
        return {}

    @property
    def namespace(self) -> str:
        return self.worker.namespace


def get_runtime_context() -> _RuntimeContext:
    return _RuntimeContext()


def timeline() -> List[Dict[str, Any]]:
    """Merged cross-process chrome-trace events (reference:
    ray timeline / state.py:414 chrome_tracing_dump)."""
    from ray_tpu.util import timeline as _tl
    return _tl.timeline_dump()
