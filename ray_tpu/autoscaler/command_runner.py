"""Command runners: how the launcher executes on cluster nodes.

Reference analogue: autoscaler/_private/command_runner.py
(SSHCommandRunner:243 — ssh/rsync with control-path reuse;
DockerCommandRunner:523 — the same surface inside a container). The
ssh binary is injectable so the updater logic is testable offline (a
fake "ssh" that drops the connection args and runs locally — the same
pattern the container runtime-env tests use).
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional, Tuple


class CommandRunner:
    def run(self, cmd: str, timeout: float = 600.0) -> Tuple[int, str]:
        raise NotImplementedError

    def run_rsync_up(self, source: str, target: str) -> Tuple[int, str]:
        raise NotImplementedError


class LocalCommandRunner(CommandRunner):
    """Runs on this host (head-node bootstrap / tests)."""

    def run(self, cmd: str, timeout: float = 600.0) -> Tuple[int, str]:
        p = subprocess.run(["bash", "-lc", cmd], capture_output=True,
                           text=True, timeout=timeout)
        return p.returncode, (p.stdout + p.stderr)

    def run_rsync_up(self, source: str, target: str) -> Tuple[int, str]:
        os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
        if os.path.isdir(source):
            shutil.copytree(source, target, dirs_exist_ok=True)
        else:
            shutil.copy2(source, target)
        return 0, ""


class SSHCommandRunner(CommandRunner):
    """ssh/scp against a node (reference: SSHCommandRunner — options
    mirror its ControlMaster-less baseline)."""

    def __init__(self, ip: str, *, user: str = "",
                 key_path: Optional[str] = None,
                 ssh_binary: str = "ssh", scp_binary: str = "scp",
                 extra_options: Optional[List[str]] = None):
        self.ip = ip
        self.user = user
        self.key_path = key_path
        self.ssh_binary = ssh_binary
        self.scp_binary = scp_binary
        self.extra_options = list(extra_options or [])

    def _target(self) -> str:
        return f"{self.user}@{self.ip}" if self.user else self.ip

    def _base_options(self) -> List[str]:
        opts = ["-o", "StrictHostKeyChecking=no",
                "-o", "UserKnownHostsFile=/dev/null",
                "-o", "ConnectTimeout=10"]
        if self.key_path:
            opts += ["-i", self.key_path]
        return opts + self.extra_options

    def run(self, cmd: str, timeout: float = 600.0) -> Tuple[int, str]:
        # real ssh space-joins the remote args and the remote shell
        # re-splits them — the command must travel as ONE quoted word
        import shlex
        argv = ([self.ssh_binary] + self._base_options()
                + [self._target(), "--", "bash", "-lc",
                   shlex.quote(cmd)])
        try:
            p = subprocess.run(argv, capture_output=True, text=True,
                               timeout=timeout)
        except subprocess.TimeoutExpired:
            return 124, f"timed out after {timeout}s: {cmd}"
        return p.returncode, (p.stdout + p.stderr)

    def run_rsync_up(self, source: str, target: str) -> Tuple[int, str]:
        argv = ([self.scp_binary] + self._base_options()
                + (["-r"] if os.path.isdir(source) else [])
                + [source, f"{self._target()}:{target}"])
        try:
            p = subprocess.run(argv, capture_output=True, text=True,
                               timeout=600)
        except subprocess.TimeoutExpired:
            return 124, "scp timed out"
        return p.returncode, (p.stdout + p.stderr)


class DockerCommandRunner(CommandRunner):
    """Same surface, inside a container on the node (reference:
    DockerCommandRunner — commands run via ``docker exec``, files land
    on the host then ``docker cp`` into the container)."""

    def __init__(self, base: CommandRunner, *, image: str,
                 container_name: str = "ray_tpu_container",
                 docker_binary: str = "docker",
                 run_options: Optional[List[str]] = None):
        self.base = base
        self.image = image
        self.container_name = container_name
        self.docker = docker_binary
        self.run_options = list(run_options or [])

    def ensure_container(self) -> Tuple[int, str]:
        opts = " ".join(self.run_options)
        return self.base.run(
            f"{self.docker} inspect {self.container_name} >/dev/null 2>&1"
            f" || {self.docker} run -d --name {self.container_name} "
            f"--network=host {opts} {self.image} sleep infinity")

    def run(self, cmd: str, timeout: float = 600.0) -> Tuple[int, str]:
        quoted = cmd.replace("'", "'\\''")
        return self.base.run(
            f"{self.docker} exec {self.container_name} "
            f"bash -lc '{quoted}'", timeout=timeout)

    def run_rsync_up(self, source: str, target: str) -> Tuple[int, str]:
        staged = f"/tmp/rtpu_stage_{os.path.basename(target)}"
        rc, out = self.base.run_rsync_up(source, staged)
        if rc != 0:
            return rc, out
        return self.base.run(
            f"{self.docker} cp {staged} "
            f"{self.container_name}:{target}")


def runner_for_node(ip: str, auth: Dict[str, Any],
                    docker: Optional[Dict[str, Any]] = None
                    ) -> CommandRunner:
    """Build the runner stack a cluster config describes (reference:
    node_provider.get_command_runner): ssh auth from the config's
    ``auth`` section, optionally wrapped in docker."""
    base: CommandRunner = SSHCommandRunner(
        ip,
        user=auth.get("ssh_user", ""),
        key_path=auth.get("ssh_private_key"),
        ssh_binary=auth.get("ssh_binary", "ssh"),
        scp_binary=auth.get("scp_binary", "scp"),
        extra_options=auth.get("ssh_options"))
    if docker and docker.get("image"):
        return DockerCommandRunner(
            base, image=docker["image"],
            container_name=docker.get("container_name",
                                      "ray_tpu_container"),
            docker_binary=docker.get("docker_binary", "docker"),
            run_options=docker.get("run_options"))
    return base
