"""NodeProvider plugin interface + fake provider for tests.

Reference analogue: autoscaler/node_provider.py (ABC) and
autoscaler/_private/fake_multi_node/node_provider.py:237
(FakeMultiNodeProvider — full autoscaler logic with no cloud: worker
"nodes" are extra raylet processes on this machine sharing the head's
GCS, exactly like the Cluster test fixture).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Cloud-agnostic node lifecycle interface."""

    def __init__(self, provider_config: Dict[str, Any]):
        self.provider_config = provider_config

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def create_node(self, node_config: Dict[str, Any],
                    count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str):
        raise NotImplementedError

    def node_resources(self, node_id: str) -> Dict[str, float]:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches worker raylets in-process against the running head."""

    def __init__(self, provider_config: Dict[str, Any]):
        super().__init__(provider_config)
        from ray_tpu._private import node as node_mod
        self._node_mod = node_mod
        self.session_dir = provider_config["session_dir"]
        self.gcs_address = provider_config["gcs_address"]
        self._nodes: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def create_node(self, node_config: Dict[str, Any],
                    count: int) -> List[str]:
        created = []
        for _ in range(count):
            info = self._node_mod.add_node(
                self.session_dir, self.gcs_address,
                resources=dict(node_config.get("resources")
                               or {"CPU": 1}),
                object_store_memory=node_config.get(
                    "object_store_memory"))
            nid = info["node_id"]
            with self._lock:
                self._nodes[nid] = info
            created.append(nid)
        return created

    def terminate_node(self, node_id: str):
        with self._lock:
            info = self._nodes.pop(node_id, None)
        if info is None:
            return
        proc = info.get("proc")
        if proc is not None:
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except Exception:
                try:
                    proc.kill()
                except Exception:
                    pass

    def node_resources(self, node_id: str) -> Dict[str, float]:
        with self._lock:
            info = self._nodes.get(node_id) or {}
        return dict(info.get("resources") or {})

    def node_pid(self, node_id: str) -> Optional[int]:
        """OS pid of the node's raylet process (launcher teardown uses
        this; the process layout stays private to the provider)."""
        with self._lock:
            info = self._nodes.get(node_id) or {}
        proc = info.get("proc")
        return proc.pid if proc is not None else None
