"""AWS EC2 node provider.

Reference analogue: autoscaler/_private/aws/node_provider.py (boto3
ec2 client: run_instances / describe_instances / terminate_instances,
cluster-name + node-kind tags). The client is injected the same way the
GCE provider injects its transport: pass ``ec2_client`` (anything with
the four boto3 methods used below) for offline use and tests; without
one, boto3 is imported lazily and the provider gates on its presence —
boto3 does not ship in this image, exactly like the reference gates on
its cloud SDKs.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

TAG_CLUSTER = "ray-tpu-cluster-name"
TAG_KIND = "ray-tpu-node-kind"


def _default_client(region: str):
    try:
        import boto3  # noqa: F401 — not in this image; deployment-only
    except ImportError as e:
        raise RuntimeError(
            "AWS provider requires boto3 (not installed) or an injected "
            "ec2_client") from e
    import boto3
    return boto3.client("ec2", region_name=region)


class AWSNodeProvider(NodeProvider):
    """Nodes are EC2 instances tagged with the cluster name."""

    def __init__(self, provider_config: Dict[str, Any], ec2_client=None):
        super().__init__(provider_config)
        self.region = provider_config.get("region", "us-west-2")
        self.cluster_name = provider_config.get("cluster_name", "rtpu")
        self.ec2 = ec2_client or _default_client(self.region)
        self._lock = threading.Lock()
        self._created_cfg: Dict[str, Dict[str, Any]] = {}

    def _cluster_filter(self) -> List[Dict[str, Any]]:
        return [
            {"Name": f"tag:{TAG_CLUSTER}", "Values": [self.cluster_name]},
            {"Name": "instance-state-name",
             "Values": ["pending", "running"]},
        ]

    def non_terminated_nodes(self) -> List[str]:
        out = self.ec2.describe_instances(Filters=self._cluster_filter())
        ids = []
        for res in out.get("Reservations", []):
            for inst in res.get("Instances", []):
                ids.append(inst["InstanceId"])
        return ids

    def create_node(self, node_config: Dict[str, Any],
                    count: int) -> List[str]:
        tags = [{"Key": TAG_CLUSTER, "Value": self.cluster_name},
                {"Key": TAG_KIND,
                 "Value": node_config.get("node_kind", "worker")}]
        params = {
            "ImageId": node_config.get("ImageId", ""),
            "InstanceType": node_config.get("InstanceType", "m5.large"),
            "MinCount": count, "MaxCount": count,
            "TagSpecifications": [{"ResourceType": "instance",
                                   "Tags": tags}],
        }
        for passthrough in ("KeyName", "SubnetId", "SecurityGroupIds",
                            "IamInstanceProfile", "UserData"):
            if node_config.get(passthrough) is not None:
                params[passthrough] = node_config[passthrough]
        out = self.ec2.run_instances(**params)
        ids = [i["InstanceId"] for i in out.get("Instances", [])]
        with self._lock:
            for i in ids:
                self._created_cfg[i] = dict(node_config)
        return ids

    def terminate_node(self, node_id: str):
        self.ec2.terminate_instances(InstanceIds=[node_id])
        with self._lock:
            self._created_cfg.pop(node_id, None)

    def node_resources(self, node_id: str) -> Dict[str, float]:
        cfg = self._created_cfg.get(node_id, {})
        if cfg.get("resources"):
            return dict(cfg["resources"])
        # conservative defaults by instance size suffix
        itype = cfg.get("InstanceType", "m5.large")
        size = itype.rsplit(".", 1)[-1]
        cpus = {"large": 2, "xlarge": 4, "2xlarge": 8, "4xlarge": 16,
                "8xlarge": 32, "12xlarge": 48, "16xlarge": 64,
                "24xlarge": 96}.get(size, 2)
        return {"CPU": float(cpus)}

    def external_ip(self, node_id: str) -> Optional[str]:
        out = self.ec2.describe_instances(InstanceIds=[node_id])
        for res in out.get("Reservations", []):
            for inst in res.get("Instances", []):
                return inst.get("PublicIpAddress") or \
                    inst.get("PrivateIpAddress")
        return None
