"""StandardAutoscaler — demand-driven node scaling.

Reference analogue: autoscaler/_private/autoscaler.py:167 (update:358)
+ load_metrics.py + resource_demand_scheduler.py: read load from the
GCS, bin-pack outstanding demand (explicit ``request_resources`` +
utilization pressure) against ``available_node_types``, launch or
terminate through the NodeProvider plugin.

TPU note: a node type with ``{"TPU": 4, "tpu_slice": ...}`` resources
scales whole slices — the provider is handed the full node_config so a
real GCE provider can request queued TPU pod resources atomically.
"""

from __future__ import annotations

import json
import math
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

_REQUEST_KEY = "@autoscaler/resource_requests"


def request_resources(bundles: List[Dict[str, float]]):
    """Explicit demand hint (reference:
    autoscaler/sdk.request_resources)."""
    import ray_tpu
    w = ray_tpu._worker_mod.global_worker()
    w.call_sync(w.gcs, "kv_put",
                {"key": _REQUEST_KEY,
                 "value": json.dumps(bundles).encode(),
                 "overwrite": True}, timeout=30)


class LoadMetrics:
    """Cluster load snapshot pulled from the GCS."""

    def __init__(self, gcs_call):
        self._call = gcs_call

    def snapshot(self) -> Dict[str, Any]:
        nodes = self._call("get_nodes", {})
        reqs_raw = self._call("kv_get",
                              {"key": _REQUEST_KEY}).get("value")
        requests = json.loads(reqs_raw) if reqs_raw else []
        return {"nodes": [n for n in nodes if n.get("alive")],
                "resource_requests": requests}


class StandardAutoscaler:
    """One `update()` per tick: launch for unmet demand, reap idle."""

    def __init__(self, provider: NodeProvider,
                 available_node_types: Dict[str, Dict[str, Any]],
                 gcs_call,
                 idle_timeout_s: float = 60.0,
                 max_launch_batch: int = 4):
        self.provider = provider
        self.node_types = available_node_types
        self.load_metrics = LoadMetrics(gcs_call)
        self.idle_timeout_s = idle_timeout_s
        self.max_launch_batch = max_launch_batch
        self._idle_since: Dict[str, float] = {}
        self._launched_type: Dict[str, str] = {}
        # node_id -> launch time; counts as capacity until it registers
        # in the GCS (or times out), so booting nodes aren't re-launched
        # for the same demand every tick
        self._pending_launches: Dict[str, float] = {}
        self.launch_timeout_s = 180.0

    # ---- demand math ----

    @staticmethod
    def _fits(bundle: Dict[str, float],
              free: Dict[str, float]) -> bool:
        return all(free.get(k, 0.0) >= v for k, v in bundle.items())

    @staticmethod
    def _sub(free: Dict[str, float], bundle: Dict[str, float]):
        for k, v in bundle.items():
            free[k] = free.get(k, 0.0) - v

    def _unmet_demand(self, snapshot) -> List[Dict[str, float]]:
        """Bundles that don't fit in current free capacity (including
        capacity of launched-but-not-yet-registered nodes)."""
        free_per_node = [dict(n.get("available") or {})
                         for n in snapshot["nodes"]]
        registered = {n["node_id"] for n in snapshot["nodes"]}
        now = time.time()
        for nid, t0 in list(self._pending_launches.items()):
            if nid in registered or now - t0 > self.launch_timeout_s:
                del self._pending_launches[nid]
                continue
            tname = self._launched_type.get(nid)
            res = (self.node_types.get(tname, {}).get("resources")
                   or {})
            free_per_node.append(dict(res))
        unmet = []
        for bundle in snapshot["resource_requests"]:
            placed = False
            for free in free_per_node:
                if self._fits(bundle, free):
                    self._sub(free, bundle)
                    placed = True
                    break
            if not placed:
                unmet.append(dict(bundle))
        return unmet

    def _plan_launches(self, unmet: List[Dict[str, float]]
                       ) -> Dict[str, int]:
        """Greedy bin-pack of unmet bundles onto new node instances
        (reference: resource_demand_scheduler.get_nodes_to_launch)."""
        plan: Dict[str, int] = {}
        counts = self._current_counts()
        fresh: List[Dict[str, float]] = []
        for bundle in unmet:
            for free in fresh:
                if self._fits(bundle, free):
                    self._sub(free, bundle)
                    break
            else:
                # pick the cheapest node type that can hold the bundle
                for tname, tcfg in sorted(
                        self.node_types.items(),
                        key=lambda kv: sum(
                            (kv[1].get("resources") or {}).values())):
                    res = tcfg.get("resources") or {}
                    maxw = tcfg.get("max_workers", 10)
                    if (self._fits(bundle, dict(res))
                            and counts.get(tname, 0)
                            + plan.get(tname, 0) < maxw):
                        plan[tname] = plan.get(tname, 0) + 1
                        free = dict(res)
                        self._sub(free, bundle)
                        fresh.append(free)
                        break
        return plan

    def _current_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for nid in self.provider.non_terminated_nodes():
            t = self._launched_type.get(nid, "_unknown")
            counts[t] = counts.get(t, 0) + 1
        return counts

    # ---- the control step ----

    def update(self) -> Dict[str, Any]:
        snapshot = self.load_metrics.snapshot()
        now = time.time()
        # 1. enforce min_workers
        counts = self._current_counts()
        launches: Dict[str, int] = {}
        for tname, tcfg in self.node_types.items():
            deficit = tcfg.get("min_workers", 0) - counts.get(tname, 0)
            if deficit > 0:
                launches[tname] = deficit
        # 2. launch for unmet explicit demand
        unmet = self._unmet_demand(snapshot)
        for tname, n in self._plan_launches(unmet).items():
            launches[tname] = launches.get(tname, 0) + n
        launched_ids: List[str] = []
        now = time.time()
        for tname, n in launches.items():
            n = min(n, self.max_launch_batch)
            cfg = self.node_types[tname]
            ids = self.provider.create_node(cfg, n)
            for nid in ids:
                self._launched_type[nid] = tname
                self._pending_launches[nid] = now
            launched_ids += ids
        # 3. reap idle workers above min_workers
        terminated: List[str] = []
        provider_nodes = set(self.provider.non_terminated_nodes())
        by_gcs = {}
        for n in snapshot["nodes"]:
            by_gcs[n["node_id"]] = n
        counts = self._current_counts()
        for nid in list(provider_nodes):
            n = by_gcs.get(nid)
            if n is None:
                continue
            res = n.get("resources") or {}
            avail = n.get("available") or {}
            # float resources (memory = fraction of host bytes) can
            # differ in the last ulp between the registration snapshot
            # and heartbeat arithmetic — exact dict equality would then
            # never see the node as idle
            idle = all(
                abs(avail.get(k, 0.0) - v) <= 1e-6 * max(1.0, abs(v))
                for k, v in res.items())
            if not idle:
                self._idle_since.pop(nid, None)
                continue
            since = self._idle_since.setdefault(nid, now)
            tname = self._launched_type.get(nid, "_unknown")
            above_min = counts.get(tname, 0) > self.node_types.get(
                tname, {}).get("min_workers", 0)
            if now - since >= self.idle_timeout_s and above_min:
                self.provider.terminate_node(nid)
                self._idle_since.pop(nid, None)
                counts[tname] -= 1
                terminated.append(nid)
        return {"launched": launched_ids, "terminated": terminated,
                "unmet_demand": unmet}


class AutoscalerMonitor:
    """Background loop driving StandardAutoscaler
    (reference: monitor.py:126 on the head node)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 interval_s: float = 5.0):
        import threading
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.autoscaler.update()
            except Exception:
                pass
            self._stop.wait(self.interval_s)

    def stop(self):
        self._stop.set()
