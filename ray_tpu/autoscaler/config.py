"""Cluster YAML config: schema, defaults, validation.

Reference analogue: autoscaler/_private/util.py (prepare_config,
validate_config against ray-schema.json) and the cluster.yaml format
(cluster_name, provider, available_node_types, head_node_type...).
"""

from __future__ import annotations

from typing import Any, Dict

PROVIDER_TYPES = ("fake_multinode", "gcp_tpu", "aws", "azure",
                  "kubernetes", "external")

_DEFAULTS: Dict[str, Any] = {
    "max_workers": 8,
    "idle_timeout_minutes": 5.0,
    "provider": {},
    "available_node_types": {},
    "head_node_type": None,
}


class ConfigError(ValueError):
    pass


def load_config(path: str) -> Dict[str, Any]:
    import yaml
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    return prepare_config(raw)


def prepare_config(config: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(_DEFAULTS)
    out.update(config or {})
    validate_config(out)
    # per-node-type defaults
    for name, nt in out["available_node_types"].items():
        nt.setdefault("min_workers", 0)
        nt.setdefault("max_workers", out["max_workers"])
        nt.setdefault("node_config", {})
        nt.setdefault("resources", {})
    return out


def validate_config(config: Dict[str, Any]):
    if not config.get("cluster_name"):
        raise ConfigError("cluster_name is required")
    provider = config.get("provider") or {}
    ptype = provider.get("type")
    if ptype not in PROVIDER_TYPES:
        raise ConfigError(
            f"provider.type must be one of {PROVIDER_TYPES}, "
            f"got {ptype!r}")
    if ptype == "gcp_tpu":
        for req in ("project_id", "availability_zone"):
            if not provider.get(req):
                raise ConfigError(f"provider.{req} is required for "
                                  "gcp_tpu")
    if ptype == "aws" and not provider.get("region"):
        raise ConfigError("provider.region is required for aws")
    if ptype == "azure":
        for req in ("subscription_id", "resource_group"):
            if not provider.get(req):
                raise ConfigError(f"provider.{req} is required for azure")
    node_types = config.get("available_node_types")
    if not isinstance(node_types, dict) or not node_types:
        raise ConfigError("available_node_types must be a non-empty dict")
    for name, nt in node_types.items():
        if not isinstance(nt, dict):
            raise ConfigError(f"node type {name!r} must be a dict")
        mn = nt.get("min_workers", 0)
        mx = nt.get("max_workers", config.get("max_workers", 8))
        if mn > mx:
            raise ConfigError(
                f"node type {name!r}: min_workers {mn} > max_workers {mx}")
    head = config.get("head_node_type")
    if head is not None and head not in node_types:
        raise ConfigError(
            f"head_node_type {head!r} not in available_node_types")


def make_provider(config: Dict[str, Any], **runtime):
    """Instantiate the provider named in the config (the registry the
    reference keeps in node_provider.py _NODE_PROVIDERS)."""
    provider = dict(config["provider"])
    ptype = provider.pop("type")
    provider["cluster_name"] = config["cluster_name"]
    provider.update(runtime)
    if ptype == "fake_multinode":
        from ray_tpu.autoscaler.node_provider import FakeMultiNodeProvider
        return FakeMultiNodeProvider(provider)
    if ptype == "gcp_tpu":
        from ray_tpu.autoscaler.gcp_tpu import GCPTPUNodeProvider
        return GCPTPUNodeProvider(provider,
                                  api_client=runtime.get("api_client"))
    if ptype == "aws":
        from ray_tpu.autoscaler.aws import AWSNodeProvider
        return AWSNodeProvider(provider,
                               ec2_client=runtime.get("ec2_client"))
    if ptype == "azure":
        from ray_tpu.autoscaler.azure import AzureNodeProvider
        return AzureNodeProvider(
            provider, compute_client=runtime.get("compute_client"))
    if ptype == "kubernetes":
        from ray_tpu.autoscaler.kubernetes import KubernetesNodeProvider
        return KubernetesNodeProvider(
            provider, k8s_client=runtime.get("k8s_client"))
    if ptype == "external":
        # provider.module = "pkg.mod:ClassName"
        mod_path = provider.get("module")
        if not mod_path:
            raise ConfigError("external provider requires provider.module")
        import importlib
        mod_name, cls_name = mod_path.split(":")
        cls = getattr(importlib.import_module(mod_name), cls_name)
        return cls(provider)
    raise ConfigError(f"no provider implementation for {ptype!r}")
