"""Kubernetes node provider + RayCluster-style operator reconcile.

Reference analogue: the KubeRay operator (ray-operator's RayCluster
CRD: head group + worker groups with replicas, reconciled against pod
state) and autoscaler/_private/kuberay/node_provider.py (nodes are
pods; the autoscaler scales worker-group ``replicas``). The k8s API
client is injected (duck-typed ``list_pods`` / ``create_pod`` /
``delete_pod`` — a thin wrapper over the core-v1 surface) so the
provider and the reconcile loop run fully offline in tests; the real
``kubernetes`` SDK is gated on presence, like the other cloud SDKs.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

LABEL_CLUSTER = "ray-tpu.io/cluster"
LABEL_GROUP = "ray-tpu.io/group"


def _default_client(namespace: str):
    try:
        import kubernetes  # noqa: F401 — deployment-only
    except ImportError as e:
        raise RuntimeError(
            "Kubernetes provider requires the kubernetes SDK (not "
            "installed) or an injected k8s_client") from e
    raise RuntimeError(
        "wrap kubernetes.client.CoreV1Api in the list_pods/create_pod/"
        "delete_pod surface and inject it as k8s_client")


class KubernetesNodeProvider(NodeProvider):
    """Nodes are pods labeled with the cluster name + group."""

    def __init__(self, provider_config: Dict[str, Any], k8s_client=None):
        super().__init__(provider_config)
        self.namespace = provider_config.get("namespace", "default")
        self.cluster_name = provider_config.get("cluster_name", "rtpu")
        self.k8s = k8s_client or _default_client(self.namespace)
        self._lock = threading.Lock()
        self._created_cfg: Dict[str, Dict[str, Any]] = {}

    def non_terminated_nodes(self) -> List[str]:
        names = []
        for pod in self.k8s.list_pods(self.namespace):
            labels = pod.get("labels") or {}
            if labels.get(LABEL_CLUSTER) != self.cluster_name:
                continue
            if pod.get("phase") in ("Succeeded", "Failed"):
                continue
            names.append(pod["name"])
        return names

    def create_node(self, node_config: Dict[str, Any],
                    count: int) -> List[str]:
        created = []
        group = node_config.get("group", "worker")
        for _ in range(count):
            name = (f"{self.cluster_name}-{group}-"
                    f"{uuid.uuid4().hex[:8]}")
            pod = {
                "name": name,
                "labels": {LABEL_CLUSTER: self.cluster_name,
                           LABEL_GROUP: group},
                "image": node_config.get(
                    "image", "ray-tpu:latest"),
                "resources": node_config.get("resources") or {},
                "command": node_config.get("command"),
                "env": node_config.get("env") or {},
            }
            self.k8s.create_pod(self.namespace, pod)
            created.append(name)
        with self._lock:
            for n in created:
                self._created_cfg[n] = dict(node_config)
        return created

    def terminate_node(self, node_id: str):
        self.k8s.delete_pod(self.namespace, node_id)
        with self._lock:
            self._created_cfg.pop(node_id, None)

    def node_resources(self, node_id: str) -> Dict[str, float]:
        cfg = self._created_cfg.get(node_id, {})
        res = dict(cfg.get("resources") or {})
        return {k: float(v) for k, v in res.items()} or {"CPU": 1.0}


class RayClusterOperator:
    """One reconcile pass of a RayCluster-style spec (the KubeRay
    controller role): ensure exactly one head pod and each worker
    group's ``replicas`` pods, deleting strays of removed groups.

    Spec shape (the RayCluster CRD essentials)::

        {"head": {"image": ..., "resources": {...}},
         "worker_groups": [
             {"name": "cpu", "replicas": 2, "image": ..., ...}]}
    """

    def __init__(self, provider: KubernetesNodeProvider):
        self.provider = provider

    def _pods_by_group(self) -> Dict[str, List[str]]:
        by_group: Dict[str, List[str]] = {}
        for pod in self.provider.k8s.list_pods(self.provider.namespace):
            labels = pod.get("labels") or {}
            if labels.get(LABEL_CLUSTER) != self.provider.cluster_name:
                continue
            if pod.get("phase") in ("Succeeded", "Failed"):
                continue
            by_group.setdefault(labels.get(LABEL_GROUP, ""),
                                []).append(pod["name"])
        return by_group

    def reconcile(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        """Drive pod state toward the spec; returns a summary of the
        actions taken (idempotent: a second pass is a no-op)."""
        actions = {"created": [], "deleted": []}
        by_group = self._pods_by_group()

        want: Dict[str, Dict[str, Any]] = {}
        head = dict(spec.get("head") or {})
        head.setdefault("replicas", 1)
        want["head"] = head
        for wg in spec.get("worker_groups") or []:
            want[wg.get("name", "worker")] = dict(wg)

        for group, cfg in want.items():
            have = by_group.get(group, [])
            target = int(cfg.get("replicas", 1))
            for _ in range(max(0, target - len(have))):
                (name,) = self.provider.create_node(
                    {**cfg, "group": group}, 1)
                actions["created"].append(name)
            for name in have[target:]:  # scale down
                self.provider.terminate_node(name)
                actions["deleted"].append(name)
        for group, pods in by_group.items():  # removed groups
            if group not in want:
                for name in pods:
                    self.provider.terminate_node(name)
                    actions["deleted"].append(name)
        return actions
