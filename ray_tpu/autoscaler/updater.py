"""Node updater: bootstrap a freshly-created node into the cluster.

Reference analogue: autoscaler/_private/updater.py NodeUpdaterThread —
wait for ssh, sync file mounts, then run initialization / setup /
start commands in order, surfacing which phase failed. Drives any
CommandRunner (ssh, ssh+docker, local), so the flow is testable with a
fake ssh binary.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.autoscaler.command_runner import CommandRunner

logger = logging.getLogger(__name__)


class NodeUpdateError(RuntimeError):
    def __init__(self, phase: str, cmd: str, rc: int, output: str):
        super().__init__(
            f"node update failed in {phase} (rc={rc}): {cmd}\n"
            f"{output[-2000:]}")
        self.phase = phase
        self.cmd = cmd
        self.rc = rc


class NodeUpdater:
    """One node's bootstrap. Phases mirror the reference's updater:
    wait_ready → file_mounts → initialization_commands →
    setup_commands → start_commands."""

    def __init__(self, runner: CommandRunner, *,
                 file_mounts: Optional[Dict[str, str]] = None,
                 initialization_commands: Optional[List[str]] = None,
                 setup_commands: Optional[List[str]] = None,
                 start_commands: Optional[List[str]] = None,
                 ready_timeout: float = 300.0):
        self.runner = runner
        self.file_mounts = dict(file_mounts or {})
        self.initialization_commands = list(initialization_commands or [])
        self.setup_commands = list(setup_commands or [])
        self.start_commands = list(start_commands or [])
        self.ready_timeout = ready_timeout
        self.phases_done: List[str] = []

    def wait_ready(self):
        deadline = time.monotonic() + self.ready_timeout
        delay = 2.0
        while True:
            rc, out = self.runner.run("uptime", timeout=30)
            if rc == 0:
                self.phases_done.append("wait_ready")
                return
            if time.monotonic() > deadline:
                raise NodeUpdateError("wait_ready", "uptime", rc, out)
            time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 1.5, 15.0)

    def _run_phase(self, phase: str, commands: List[str]):
        for cmd in commands:
            rc, out = self.runner.run(cmd)
            if rc != 0:
                raise NodeUpdateError(phase, cmd, rc, out)
        self.phases_done.append(phase)

    def sync_file_mounts(self):
        for target, source in self.file_mounts.items():
            rc, out = self.runner.run_rsync_up(source, target)
            if rc != 0:
                raise NodeUpdateError("file_mounts",
                                      f"{source} -> {target}", rc, out)
        self.phases_done.append("file_mounts")

    def update(self):
        """The full bootstrap; raises NodeUpdateError naming the phase
        that failed."""
        self.wait_ready()
        if hasattr(self.runner, "ensure_container"):
            rc, out = self.runner.ensure_container()
            if rc != 0:
                raise NodeUpdateError("docker", "ensure_container", rc,
                                      out)
            self.phases_done.append("docker")
        self.sync_file_mounts()
        self._run_phase("initialization_commands",
                        self.initialization_commands)
        self._run_phase("setup_commands", self.setup_commands)
        self._run_phase("start_commands", self.start_commands)


def update_node_from_config(ip: str, cfg: Dict[str, Any], *,
                            is_head: bool) -> NodeUpdater:
    """Build and run the updater a cluster YAML describes for one node
    (reference: the up flow handing each created node to
    NodeUpdaterThread). Returns the updater (phases_done inspectable)."""
    from ray_tpu.autoscaler.command_runner import runner_for_node
    runner = runner_for_node(ip, cfg.get("auth") or {},
                             docker=cfg.get("docker"))
    start = cfg.get("head_start_ray_commands" if is_head
                    else "worker_start_ray_commands") or \
        cfg.get("start_commands") or []
    updater = NodeUpdater(
        runner,
        file_mounts=cfg.get("file_mounts"),
        initialization_commands=cfg.get("initialization_commands"),
        setup_commands=cfg.get("setup_commands"),
        start_commands=start)
    updater.update()
    return updater
