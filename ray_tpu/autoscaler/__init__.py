"""ray_tpu.autoscaler — demand-driven cluster scaling
(reference: autoscaler/_private/autoscaler.py + node providers)."""

from ray_tpu.autoscaler.autoscaler import (AutoscalerMonitor,
                                           LoadMetrics,
                                           StandardAutoscaler,
                                           request_resources)
from ray_tpu.autoscaler.node_provider import (FakeMultiNodeProvider,
                                              NodeProvider)

__all__ = ["StandardAutoscaler", "AutoscalerMonitor", "LoadMetrics",
           "request_resources", "NodeProvider", "FakeMultiNodeProvider"]
