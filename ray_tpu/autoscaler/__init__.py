"""ray_tpu.autoscaler — demand-driven cluster scaling
(reference: autoscaler/_private/autoscaler.py + node providers)."""

from ray_tpu.autoscaler.autoscaler import (AutoscalerMonitor,
                                           LoadMetrics,
                                           StandardAutoscaler,
                                           request_resources)
from ray_tpu.autoscaler.node_provider import (FakeMultiNodeProvider,
                                              NodeProvider)
from ray_tpu.autoscaler.config import (ConfigError, load_config,
                                       make_provider, prepare_config,
                                       validate_config)
from ray_tpu.autoscaler.gcp_tpu import GCPTPUNodeProvider
from ray_tpu.autoscaler.commands import (create_or_update_cluster,
                                         teardown_cluster)

__all__ = ["StandardAutoscaler", "AutoscalerMonitor", "LoadMetrics",
           "request_resources", "NodeProvider", "FakeMultiNodeProvider",
           "GCPTPUNodeProvider", "load_config", "prepare_config",
           "validate_config", "make_provider", "ConfigError",
           "create_or_update_cluster", "teardown_cluster"]
