"""Azure VM node provider.

Reference analogue: autoscaler/_private/_azure/node_provider.py (the
azure-mgmt-compute SDK, VMs tagged by cluster name). Same injected-
transport discipline as the AWS/GCE providers: pass ``compute_client``
(duck-typed: ``list_vms`` / ``create_vm`` / ``delete_vm``, shaped like
a thin wrapper over azure.mgmt.compute) for offline use and tests; the
real SDK is imported lazily and gated on presence.
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider

TAG_CLUSTER = "ray-tpu-cluster-name"


def _default_client(subscription_id: str, resource_group: str):
    try:
        import azure.mgmt.compute  # noqa: F401 — deployment-only
    except ImportError as e:
        raise RuntimeError(
            "Azure provider requires azure-mgmt-compute (not installed) "
            "or an injected compute_client") from e
    raise RuntimeError(
        "wrap azure.mgmt.compute in the list_vms/create_vm/delete_vm "
        "surface and inject it as compute_client")


class AzureNodeProvider(NodeProvider):
    """Nodes are Azure VMs tagged with the cluster name."""

    def __init__(self, provider_config: Dict[str, Any],
                 compute_client=None):
        super().__init__(provider_config)
        self.subscription_id = provider_config.get("subscription_id", "")
        self.resource_group = provider_config.get("resource_group", "")
        self.location = provider_config.get("location", "westus2")
        self.cluster_name = provider_config.get("cluster_name", "rtpu")
        self.compute = compute_client or _default_client(
            self.subscription_id, self.resource_group)
        self._lock = threading.Lock()
        self._created_cfg: Dict[str, Dict[str, Any]] = {}

    def non_terminated_nodes(self) -> List[str]:
        ids = []
        for vm in self.compute.list_vms(self.resource_group):
            tags = vm.get("tags") or {}
            if tags.get(TAG_CLUSTER) != self.cluster_name:
                continue
            if vm.get("provisioning_state") in ("Deleting", "Failed"):
                continue
            ids.append(vm["name"])
        return ids

    def create_node(self, node_config: Dict[str, Any],
                    count: int) -> List[str]:
        created = []
        for _ in range(count):
            name = f"{self.cluster_name}-{uuid.uuid4().hex[:8]}"
            spec = {
                "name": name,
                "location": self.location,
                "vm_size": node_config.get("vm_size", "Standard_D2s_v3"),
                "image": node_config.get("image", {}),
                "tags": {TAG_CLUSTER: self.cluster_name,
                         "ray-tpu-node-kind":
                             node_config.get("node_kind", "worker")},
            }
            for passthrough in ("admin_username", "ssh_public_key",
                                "subnet_id", "user_data"):
                if node_config.get(passthrough) is not None:
                    spec[passthrough] = node_config[passthrough]
            self.compute.create_vm(self.resource_group, spec)
            created.append(name)
        with self._lock:
            for n in created:
                self._created_cfg[n] = dict(node_config)
        return created

    def terminate_node(self, node_id: str):
        self.compute.delete_vm(self.resource_group, node_id)
        with self._lock:
            self._created_cfg.pop(node_id, None)

    def node_resources(self, node_id: str) -> Dict[str, float]:
        cfg = self._created_cfg.get(node_id, {})
        if cfg.get("resources"):
            return dict(cfg["resources"])
        vm_size = cfg.get("vm_size", "Standard_D2s_v3")
        # Standard_D<N>s_v3-style names carry the vCPU count in the
        # FIRST digit run ("D8s_v3" -> 8, not 83)
        import re
        m = re.search(r"\d+", vm_size.split("_", 1)[-1])
        return {"CPU": float(m.group(0)) if m else 2.0}

    def external_ip(self, node_id: str) -> Optional[str]:
        for vm in self.compute.list_vms(self.resource_group):
            if vm["name"] == node_id:
                return vm.get("public_ip") or vm.get("private_ip")
        return None
