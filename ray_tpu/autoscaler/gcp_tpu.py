"""GCE TPU-VM node provider (queued resources).

Reference analogue: autoscaler/_private/gcp/node_provider.py + the TPU
pod support in autoscaler/_private/gcp/config.py. Talks to the Cloud TPU
v2 API (projects.locations.queuedResources) — each "node" is a whole TPU
pod slice requested atomically, the right granularity for gang-scheduled
ICI domains (SURVEY §2.5).

The HTTP transport is injected (``api_client``) so the provider logic is
fully testable offline; the default client authenticates via the GCE
metadata server token (the standard in-cluster path).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.common.tpu import slice_topology


class TPUApiClient:
    """Minimal Cloud TPU v2 REST transport (metadata-server auth)."""

    BASE = "https://tpu.googleapis.com/v2"

    def __init__(self, project: str, zone: str):
        self.project = project
        self.zone = zone
        self._token: Optional[str] = None
        self._token_expiry = 0.0

    def _auth_header(self) -> Dict[str, str]:
        import json
        import urllib.request
        if self._token is None or time.time() > self._token_expiry - 60:
            req = urllib.request.Request(
                "http://metadata.google.internal/computeMetadata/v1/"
                "instance/service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"})
            data = json.loads(urllib.request.urlopen(
                req, timeout=10).read())
            self._token = data["access_token"]
            self._token_expiry = time.time() + data.get("expires_in", 300)
        return {"Authorization": f"Bearer {self._token}"}

    def _url(self, path: str) -> str:
        return (f"{self.BASE}/projects/{self.project}/locations/"
                f"{self.zone}/{path}")

    def request(self, method: str, path: str,
                body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        import json
        import urllib.request
        req = urllib.request.Request(
            self._url(path), method=method,
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     **self._auth_header()})
        return json.loads(urllib.request.urlopen(req, timeout=60).read())


class GCPTPUNodeProvider(NodeProvider):
    """Nodes are TPU queued-resource requests; node ids are the
    queued-resource names."""

    def __init__(self, provider_config: Dict[str, Any],
                 api_client=None):
        super().__init__(provider_config)
        self.project = provider_config.get("project_id", "")
        self.zone = provider_config.get("availability_zone",
                                        provider_config.get("zone", ""))
        self.cluster_name = provider_config.get("cluster_name", "rtpu")
        self.api = api_client or TPUApiClient(self.project, self.zone)
        self._lock = threading.Lock()
        # node id -> node_config used at creation (for node_resources)
        self._created_cfg: Dict[str, Dict[str, Any]] = {}

    # ---- NodeProvider API ----

    def non_terminated_nodes(self) -> List[str]:
        out = self.api.request("GET", "queuedResources")
        ids = []
        for qr in out.get("queuedResources", []):
            name = qr.get("name", "").rsplit("/", 1)[-1]
            if not name.startswith(f"{self.cluster_name}-"):
                continue
            state = (qr.get("state") or {}).get("state", "")
            if state not in ("FAILED", "SUSPENDED"):
                ids.append(name)
        return ids

    def create_node(self, node_config: Dict[str, Any],
                    count: int) -> List[str]:
        created = []
        acc_type = node_config.get("acceleratorType", "v5litepod-8")
        runtime = node_config.get("runtimeVersion", "tpu-ubuntu2204-base")
        for _ in range(count):
            name = f"{self.cluster_name}-{uuid.uuid4().hex[:8]}"
            body = {
                "tpu": {"nodeSpec": [{
                    "parent": f"projects/{self.project}/locations/"
                              f"{self.zone}",
                    "nodeId": name,
                    "node": {
                        "acceleratorType": acc_type,
                        "runtimeVersion": runtime,
                        "networkConfig": node_config.get(
                            "networkConfig",
                            {"enableExternalIps": False}),
                        "metadata": {
                            "rtpu-cluster": self.cluster_name,
                            **(node_config.get("metadata") or {}),
                        },
                    },
                }]},
            }
            if node_config.get("reserved"):
                body["guaranteed"] = {"reserved": True}
            elif node_config.get("spot"):
                body["spot"] = {}
            else:
                body["bestEffort"] = {}
            self.api.request(
                "POST", f"queuedResources?queuedResourceId={name}", body)
            with self._lock:
                self._created_cfg[name] = dict(node_config)
            created.append(name)
        return created

    def terminate_node(self, node_id: str):
        try:
            self.api.request("DELETE",
                             f"queuedResources/{node_id}?force=true")
        finally:
            with self._lock:
                self._created_cfg.pop(node_id, None)

    def _accelerator_type(self, node_id: str) -> str:
        with self._lock:
            cfg = self._created_cfg.get(node_id)
        if cfg is not None:
            return cfg.get("acceleratorType", "")
        # a fresh provider instance (monitor restart, `down` in a new
        # process) recovers the slice spec from the API
        try:
            qr = self.api.request("GET", f"queuedResources/{node_id}")
            node = (qr.get("tpu") or {}).get("nodeSpec", [{}])[0].get(
                "node", {})
            return node.get("acceleratorType", "")
        except Exception:
            return ""

    def node_resources(self, node_id: str) -> Dict[str, float]:
        acc = self._accelerator_type(node_id)
        # common/tpu.py is the single source of truth for the
        # accelerator-type suffix (TensorCores on v2/v3/v4/v5p, chips on
        # v5e/v6e) so advertised capacity matches what the slice's
        # raylets will register via _chips_from_accel_type.
        topo = slice_topology(acc)
        if topo is None:
            return {"TPU": 0.0}
        chips, hosts = topo
        return {"TPU": float(chips),
                "CPU": 96.0 * hosts,  # typical TPU-VM host vCPUs
                "tpu_slice": 1.0}

    def node_state(self, node_id: str) -> str:
        out = self.api.request("GET", f"queuedResources/{node_id}")
        return (out.get("state") or {}).get("state", "UNKNOWN")
