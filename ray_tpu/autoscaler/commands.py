"""`ray-tpu up` / `ray-tpu down`: cluster lifecycle from a YAML config.

Reference analogue: autoscaler/_private/commands.py
(create_or_update_cluster:186, teardown_cluster:332). The fake_multinode
provider gives the full experience on one machine (detached head process
+ worker raylets); the gcp_tpu provider provisions queued TPU-pod
resources (in-VM bootstrap is printed, not SSH-executed — zero-egress
environments can't reach the VMs anyway).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, Optional, Union

from ray_tpu.autoscaler.config import (ConfigError, load_config,
                                       make_provider, prepare_config)

STATE_DIR = os.path.expanduser("~/.ray_tpu/clusters")


def _state_path(cluster_name: str) -> str:
    os.makedirs(STATE_DIR, exist_ok=True)
    return os.path.join(STATE_DIR, f"{cluster_name}.json")


def _load_state(cluster_name: str) -> Optional[Dict[str, Any]]:
    p = _state_path(cluster_name)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def _save_state(cluster_name: str, state: Dict[str, Any]):
    with open(_state_path(cluster_name), "w") as f:
        json.dump(state, f, indent=1)


def _resolve(config: Union[str, Dict[str, Any]]) -> Dict[str, Any]:
    if isinstance(config, str):
        return load_config(config)
    return prepare_config(config)


def _start_detached_head(config: Dict[str, Any]) -> Dict[str, Any]:
    """Spawn `ray-tpu start --head --block` detached; wait for the GCS
    address to appear in its log."""
    import tempfile
    log = tempfile.NamedTemporaryFile(
        prefix="rtpu_head_", suffix=".log", delete=False)
    head_type = config.get("head_node_type")
    res = {}
    if head_type:
        res = config["available_node_types"][head_type].get(
            "resources") or {}
    cmd = [sys.executable, "-m", "ray_tpu.scripts.cli", "start", "--head",
           "--block"]
    if res.get("CPU"):
        cmd += ["--num-cpus", str(res["CPU"])]
    if res.get("TPU"):
        cmd += ["--num-tpus", str(res["TPU"])]
    proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            start_new_session=True)
    deadline = time.time() + 120
    address = None
    while time.time() < deadline:
        with open(log.name) as f:
            for line in f:
                if line.startswith("export RTPU_ADDRESS="):
                    address = line.strip().split("=", 1)[1]
                    break
        if address or proc.poll() is not None:
            break
        time.sleep(0.5)
    if address is None:
        proc.kill()
        raise RuntimeError(
            f"head failed to start; log: {log.name}")
    return {"pid": proc.pid, "gcs_address": address, "log": log.name}


def create_or_update_cluster(
        config: Union[str, Dict[str, Any]], *,
        api_client=None, ec2_client=None,
        compute_client=None) -> Dict[str, Any]:
    """Bring the cluster to its configured min size. Returns the state
    dict (also persisted for `ray-tpu down`)."""
    provider_runtime = {"api_client": api_client,
                        "ec2_client": ec2_client,
                        "compute_client": compute_client}
    cfg = _resolve(config)
    name = cfg["cluster_name"]
    ptype = cfg["provider"]["type"]
    # IDEMPOTENT: re-running `up` reconciles against the persisted state
    # instead of provisioning a second (leaked, billable) cluster
    state: Dict[str, Any] = _load_state(name) or {
        "cluster_name": name, "provider": ptype, "nodes": {}}

    def _pid_alive(pid) -> bool:
        if not pid:
            return False
        try:
            os.kill(pid, 0)
            return True
        except OSError:
            return False

    if ptype == "fake_multinode":
        try:
            head = state.get("head")
            if not head or not _pid_alive(head.get("pid")):
                head = _start_detached_head(cfg)
                state["head"] = head
                # persist the head IMMEDIATELY: a later failure must not
                # orphan the process with no record for `down`
                _save_state(name, state)
            from ray_tpu._private import node as node_mod
            session_dir = node_mod.new_session_dir()
            provider = make_provider(cfg, session_dir=session_dir,
                                     gcs_address=head["gcs_address"])
            # drop dead workers from the record before computing deltas
            state["nodes"] = {nid: info for nid, info
                              in state["nodes"].items()
                              if _pid_alive(info.get("pid"))}
            for tname, nt in cfg["available_node_types"].items():
                if tname == cfg.get("head_node_type"):
                    continue
                have = sum(1 for s in state["nodes"].values()
                           if s["type"] == tname)
                for _ in range(max(0, nt.get("min_workers", 0) - have)):
                    (nid,) = provider.create_node(
                        {"resources": nt.get("resources") or {"CPU": 1},
                         **nt.get("node_config", {})}, 1)
                    state["nodes"][nid] = {
                        "type": tname, "pid": provider.node_pid(nid)}
                    _save_state(name, state)
        finally:
            _save_state(name, state)
        return state

    if ptype == "gcp_tpu":
        provider = make_provider(cfg, api_client=api_client)
        try:
            live = set(provider.non_terminated_nodes())
            state["nodes"] = {nid: info for nid, info
                              in state["nodes"].items() if nid in live}
            for tname, nt in cfg["available_node_types"].items():
                target = nt.get("min_workers", 0)
                if tname == cfg.get("head_node_type"):
                    target = max(target, 1)  # the head slice must exist
                have = sum(1 for s in state["nodes"].values()
                           if s["type"] == tname)
                for _ in range(max(0, target - have)):
                    (nid,) = provider.create_node(
                        nt.get("node_config") or {}, 1)
                    state["nodes"][nid] = {"type": tname}
                    # every billable slice lands in the state file the
                    # moment it is requested
                    _save_state(name, state)
            state["bootstrap"] = (
                "queued resources requested; once ACTIVE, run "
                "`ray-tpu start --head` on the head slice and "
                "`ray-tpu start --address <head>` on workers "
                "(setup_commands from the config apply)")
        finally:
            _save_state(name, state)
        return state

    if ptype in ("aws", "azure"):
        provider = make_provider(cfg, **provider_runtime)
        try:
            live = set(provider.non_terminated_nodes())
            state["nodes"] = {nid: info for nid, info
                              in state["nodes"].items() if nid in live}
            created: list = []
            # phase 1: create every missing node (fast API calls)
            for tname, nt in cfg["available_node_types"].items():
                target = nt.get("min_workers", 0)
                if tname == cfg.get("head_node_type"):
                    target = max(target, 1)
                have = sum(1 for s in state["nodes"].values()
                           if s["type"] == tname)
                for _ in range(max(0, target - have)):
                    (nid,) = provider.create_node(
                        nt.get("node_config") or {}, 1)
                    state["nodes"][nid] = {"type": tname}
                    created.append((nid, tname))
                    _save_state(name, state)
            # phase 2: bootstrap CONCURRENTLY (reference: one
            # NodeUpdaterThread per node — a single unreachable node
            # must not serialize the whole cluster behind its
            # ready_timeout)
            if created and (cfg.get("auth") or cfg.get(
                    "setup_commands") or cfg.get("file_mounts")):
                from concurrent.futures import ThreadPoolExecutor
                from ray_tpu.autoscaler.updater import (
                    NodeUpdateError, update_node_from_config)

                def _bootstrap(item):
                    nid, tname = item
                    ip = provider.external_ip(nid)
                    if not ip:
                        return nid, None, "no reachable ip"
                    try:
                        upd = update_node_from_config(
                            ip, cfg, is_head=(
                                tname == cfg.get("head_node_type")))
                        return nid, upd.phases_done, None
                    except NodeUpdateError as e:
                        return nid, None, str(e)[:500]

                with ThreadPoolExecutor(max_workers=8) as pool:
                    for nid, phases, err in pool.map(_bootstrap,
                                                     created):
                        if phases is not None:
                            state["nodes"][nid]["bootstrap"] = phases
                        if err is not None:
                            state["nodes"][nid]["bootstrap_error"] = err
                        _save_state(name, state)
        finally:
            _save_state(name, state)
        return state

    raise ConfigError(f"ray-tpu up does not support provider {ptype!r}")


def teardown_cluster(config: Union[str, Dict[str, Any]], *,
                     api_client=None, ec2_client=None,
                     compute_client=None) -> int:
    """Terminate every node `up` created. Returns nodes torn down."""
    provider_runtime = {"api_client": api_client,
                        "ec2_client": ec2_client,
                        "compute_client": compute_client}
    cfg = _resolve(config)
    name = cfg["cluster_name"]
    state = _load_state(name)
    if state is None:
        return 0
    n = 0
    ptype = state.get("provider")
    if ptype == "fake_multinode":
        import signal
        # workers: direct SIGTERM per pid (they may share the caller's
        # process group — killpg would take the caller down too)
        for nid, info in state.get("nodes", {}).items():
            pid = info.get("pid")
            if pid:
                try:
                    os.kill(pid, signal.SIGTERM)
                except Exception:
                    pass
                n += 1
        head = state.get("head") or {}
        if head.get("pid"):
            # the head got its own session (start_new_session=True): take
            # down its whole group (GCS/raylet/workers it spawned)
            try:
                os.killpg(os.getpgid(head["pid"]), signal.SIGTERM)
            except Exception:
                try:
                    os.kill(head["pid"], signal.SIGKILL)
                except Exception:
                    pass
            n += 1
    elif ptype in ("gcp_tpu", "aws", "azure"):
        provider = make_provider(cfg, **provider_runtime)
        for nid in list(state.get("nodes", {})):
            try:
                provider.terminate_node(nid)
                n += 1
                # prune per node: a failed later termination must not
                # lose the record of the ones still running (billable!)
                state["nodes"].pop(nid, None)
                _save_state(name, state)
            except Exception:
                pass
        if state.get("nodes"):
            # terminations failed: keep the state file so a retried
            # `down` still knows which nodes exist
            return n
    try:
        os.remove(_state_path(name))
    except OSError:
        pass
    return n
