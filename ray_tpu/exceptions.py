"""Public exception hierarchy.

Role-equivalent to the reference's python/ray/exceptions.py: errors raised in a
remote task/actor are captured with their traceback, shipped through the object
plane, and re-raised at ``get()`` wrapped in ``TaskError``/``ActorError``.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised; re-raised at get() with the remote traceback."""

    def __init__(self, function_name: str, cause: Exception | None = None,
                 remote_traceback: str = ""):
        self.function_name = function_name
        self.cause = cause
        self.remote_traceback = remote_traceback
        super().__init__(self._format())

    def _format(self):
        msg = f"Task {self.function_name!r} failed"
        if self.cause is not None:
            msg += f": {type(self.cause).__name__}: {self.cause}"
        if self.remote_traceback:
            msg += "\n\nRemote traceback:\n" + self.remote_traceback
        return msg

    @classmethod
    def capture(cls, function_name: str, exc: Exception) -> "TaskError":
        return cls(function_name, exc, traceback.format_exc())


class ActorError(TaskError):
    """An actor method raised, or the actor is unreachable."""


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id=None, reason: str = "actor died"):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id} died: {reason}")


class ActorUnavailableError(RayTpuError):
    pass


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ObjectLostError(RayTpuError):
    """Object's copies are gone and lineage reconstruction failed/disabled."""

    def __init__(self, object_id=None, reason: str = ""):
        self.object_id = object_id
        super().__init__(f"Object {object_id} lost. {reason}")


class ObjectStoreFullError(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    """Task killed by the node memory monitor."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled")


class TaskUnschedulableError(RayTpuError):
    """No node can ever satisfy the task's resource demand."""


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class NodeDiedError(RayTpuError):
    pass


class SliceDownError(RayTpuError):
    """A TPU slice lost a host: all gang members on that slice are failed
    together (ICI collectives are gang-fatal; see SURVEY.md §5.3 TPU note)."""


class CrossLanguageError(RayTpuError):
    pass
