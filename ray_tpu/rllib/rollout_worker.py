"""RolloutWorker + WorkerSet — sampling actors.

Reference analogue: rllib/evaluation/rollout_worker.py:153 (sample :856)
and worker_set.py:77 (sync_weights :381). TPU-first shape: the worker
steps a synchronous VectorEnv and runs ONE batched jitted policy forward
per env-step; fragments are cut at ``rollout_fragment_length`` and GAE is
computed worker-side so the learner only sees ready-to-train columns.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import VectorEnv, make_env
from ray_tpu.rllib.sample_batch import SampleBatch


class RolloutWorker:
    """Samples experience from a vectorized env with a local policy copy."""

    def __init__(self, config: Dict[str, Any], policy_cls,
                 worker_index: int = 0):
        self.config = config
        self.worker_index = worker_index
        env_fn = lambda: make_env(config["env"], config.get("env_config"))
        self.vector_env = VectorEnv(
            env_fn, config.get("num_envs_per_worker", 1),
            seed=(config.get("seed") or 0) * 10_000 + worker_index)
        # connector pipelines: obs transforms before the policy forward,
        # action transforms before env.step (rllib/connectors.py). The
        # policy sees the PIPELINE's output space (frame stacking /
        # resizing change shapes), not the raw env space.
        from ray_tpu.rllib.connectors import build_connectors
        self.obs_connectors, self.action_connectors = \
            build_connectors(config)
        self.policy = policy_cls(
            self.obs_connectors.observation_space(
                self.vector_env.observation_space),
            self.vector_env.action_space, config)
        self._obs = self.vector_env.reset_all()
        # processed view of _obs, cached so stateful connectors (MeanStd)
        # see each observation exactly once
        self._proc_obs = self.obs_connectors(self._obs)
        n = self.vector_env.num_envs
        self._eps_ids = np.arange(n, dtype=np.int64) * 1_000_000 \
            + worker_index
        self._next_eps = self._eps_ids.max() + 1
        self._episode_rewards = np.zeros(n, np.float64)
        self._episode_lens = np.zeros(n, np.int64)
        self._completed_rewards: List[float] = []
        self._completed_lens: List[int] = []

    def sample(self) -> SampleBatch:
        """Collect ``rollout_fragment_length`` steps from every sub-env."""
        frag_len = self.config.get("rollout_fragment_length", 200)
        n_envs = self.vector_env.num_envs
        cols: Dict[str, list] = {
            k: [] for k in (SampleBatch.OBS, SampleBatch.ACTIONS,
                            SampleBatch.REWARDS, SampleBatch.DONES,
                            SampleBatch.TRUNCATEDS, SampleBatch.NEXT_OBS,
                            SampleBatch.EPS_ID)}
        explore = self.config.get("explore", True)
        has_obs_conn = bool(self.obs_connectors.connectors)
        for _ in range(frag_len):
            proc_obs = self._proc_obs
            actions, extras = self.policy.compute_actions(
                proc_obs, explore=explore)
            env_actions = self.action_connectors(actions)
            next_obs, rews, terms, truncs, infos = self.vector_env.step(
                env_actions)
            true_next = next_obs.copy()
            for i, info in enumerate(infos):
                if "terminal_observation" in info:
                    true_next[i] = info["terminal_observation"]
            if has_obs_conn:
                # ORDER MATTERS: the TRUE next obs (incl.
                # terminal_observation rows, which truncated-episode
                # bootstrapping reads) goes through a state-preserving
                # transform against the PRE-step connector state (frame
                # stacks must not have restarted yet, running stats
                # must not count rows twice) — only then does the
                # stateful pass advance, restarting auto-reset slots
                true_next = np.asarray(
                    self.obs_connectors.transform(true_next))
            proc_next = self.obs_connectors(next_obs,
                                            dones=terms | truncs)
            # the batch records the PROCESSED obs (what the policy saw)
            # and the RAW actions (what logp corresponds to)
            cols[SampleBatch.OBS].append(np.asarray(proc_obs).copy())
            cols[SampleBatch.ACTIONS].append(actions)
            cols[SampleBatch.REWARDS].append(rews)
            cols[SampleBatch.DONES].append(terms)
            cols[SampleBatch.TRUNCATEDS].append(truncs)
            cols[SampleBatch.NEXT_OBS].append(true_next)
            cols[SampleBatch.EPS_ID].append(self._eps_ids.copy())
            # every policy extra (logp, dist inputs, vf preds, algo-
            # specific columns like SAC's raw_actions) becomes a column
            for k, v in extras.items():
                cols.setdefault(k, []).append(v)
            self._episode_rewards += rews
            self._episode_lens += 1
            finished = terms | truncs
            for i in np.nonzero(finished)[0]:
                self._completed_rewards.append(
                    float(self._episode_rewards[i]))
                self._completed_lens.append(int(self._episode_lens[i]))
                self._episode_rewards[i] = 0.0
                self._episode_lens[i] = 0
                self._eps_ids[i] = self._next_eps
                self._next_eps += 1
            self._obs = next_obs
            self._proc_obs = proc_next

        # [T, N, ...] → per-env trajectories → policy postprocess (GAE
        # for PPO, no-op for DQN/IMPALA) → concat.
        stacked = {k: np.stack(v) for k, v in cols.items()}
        frags = []
        for i in range(n_envs):
            env_cols = SampleBatch(
                {k: v[:, i] for k, v in stacked.items()})
            for ep in env_cols.split_by_episode():
                frags.append(self.policy.postprocess_trajectory(ep))
        return SampleBatch.concat_samples(frags)

    def sample_with_count(self):
        b = self.sample()
        return b, b.count

    def evaluate_episodes(self, num_episodes: int) -> List[float]:
        """Greedy episodes on a fresh env (evaluation WorkerSet duty —
        reference: algorithm.py _evaluate_async worker rollouts)."""
        env = make_env(self.config["env"], self.config.get("env_config"))
        rewards = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=50_000 + self.worker_index * 1000 + ep)
            # per-episode pipeline clone: running stats are shared with
            # training, per-episode state (frame stacks) restarts — and
            # the training-time stacks are never polluted
            pipeline = self.obs_connectors.clone_for_eval()
            total, done = 0.0, False
            while not done:
                proc = pipeline(np.asarray(obs)[None])
                a, _ = self.policy.compute_actions(proc, explore=False)
                a = self.action_connectors.transform(a)
                obs, r, term, trunc, _ = env.step(a[0])
                total += float(r)
                done = term or trunc
            rewards.append(total)
        return rewards

    def get_connector_state(self):
        return {"obs": self.obs_connectors.state(),
                "actions": self.action_connectors.state()}

    def set_connector_state(self, state):
        if not state:
            return
        self.obs_connectors.set_state(state.get("obs") or [])
        self.action_connectors.set_state(state.get("actions") or [])

    # ---- weights / metrics / state ----

    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights):
        self.policy.set_weights(weights)

    def get_metrics(self) -> Dict[str, Any]:
        out = {
            "episode_rewards": list(self._completed_rewards),
            "episode_lens": list(self._completed_lens),
        }
        self._completed_rewards = []
        self._completed_lens = []
        return out

    def apply(self, fn, *args):
        """Run ``fn(worker, *args)`` inside this worker (reference:
        RolloutWorker.apply) — worker-side gradient computation (A3C),
        local SGD (DDPPO), knob propagation."""
        return fn(self, *args)

    def set_exploration(self, **attrs):
        for k, v in attrs.items():
            setattr(self.policy, k, v)

    def get_policy_state(self):
        return self.policy.get_state()

    def set_policy_state(self, state):
        self.policy.set_state(state)

    def ping(self) -> str:
        return "ok"

    def stop(self):
        pass


class WorkerSet:
    """Local learner worker + N remote rollout actors
    (reference: rllib/evaluation/worker_set.py:77)."""

    def __init__(self, config: Dict[str, Any], policy_cls,
                 num_workers: int):
        self.config = config
        self.policy_cls = policy_cls
        worker_cls: type = RolloutWorker
        if (config.get("multiagent") or {}).get("policies"):
            worker_cls = MultiAgentRolloutWorker
        self.local_worker = worker_cls(config, policy_cls, worker_index=0)
        self.remote_workers: List[Any] = []
        if num_workers > 0:
            remote_cls = ray_tpu.remote(
                num_cpus=config.get("num_cpus_per_worker", 1))(worker_cls)
            self.remote_workers = [
                remote_cls.remote(config, policy_cls, worker_index=i + 1)
                for i in range(num_workers)]

    def sync_weights(self):
        """Broadcast learner weights via ONE object-store put
        (reference: worker_set.py:381 + ppo.py:345)."""
        if not self.remote_workers:
            return
        ref = ray_tpu.put(self.local_worker.get_weights())
        ray_tpu.get([w.set_weights.remote(ref)
                     for w in self.remote_workers])

    def set_exploration(self, **attrs):
        """Propagate exploration knobs (e.g. epsilon) to every policy copy,
        local and remote."""
        self.local_worker.set_exploration(**attrs)
        if self.remote_workers:
            ray_tpu.get([w.set_exploration.remote(**attrs)
                         for w in self.remote_workers])

    def sample_all(self) -> List[SampleBatch]:
        if not self.remote_workers:
            return [self.local_worker.sample()]
        return ray_tpu.get([w.sample.remote() for w in self.remote_workers])

    def collect_metrics(self) -> List[Dict[str, Any]]:
        out = [self.local_worker.get_metrics()]
        if self.remote_workers:
            out += ray_tpu.get(
                [w.get_metrics.remote() for w in self.remote_workers])
        return out

    def stop(self):
        for w in self.remote_workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


def synchronous_parallel_sample(worker_set: WorkerSet,
                                max_env_steps: Optional[int] = None
                                ) -> SampleBatch:
    """Keep sampling rounds until ``max_env_steps`` collected
    (reference: rllib/execution/rollout_ops.py:21)."""
    batches: List[SampleBatch] = []
    steps = 0
    target = max_env_steps or 1
    while steps < target:
        round_batches = worker_set.sample_all()
        for b in round_batches:
            batches.append(b)
            steps += b.count
        if max_env_steps is None:
            break
    from ray_tpu.rllib.sample_batch import MultiAgentBatch
    if batches and isinstance(batches[0], MultiAgentBatch):
        return MultiAgentBatch.concat_samples(batches)
    return SampleBatch.concat_samples(batches)


class MultiAgentRolloutWorker:
    """Samples a MultiAgentEnv with one policy per policy-id.

    Reference analogue: rollout_worker.py multi-agent path +
    policy_map.py. Per env-step, agents are grouped by mapped policy and
    each policy runs ONE batched forward over its agents.
    """

    def __init__(self, config: Dict[str, Any], policy_cls,
                 worker_index: int = 0):
        from ray_tpu.rllib.env import make_env
        self.config = config
        self.worker_index = worker_index
        self.env = make_env(config["env"], config.get("env_config"))
        ma = config.get("multiagent") or {}
        self.policy_mapping_fn = ma.get(
            "policy_mapping_fn", lambda aid, **kw: "default_policy")
        self.policies_to_train = ma.get("policies_to_train")
        specs = ma.get("policies") or {"default_policy": (None, None,
                                                          None, {})}
        self.policy_map: Dict[str, Any] = {}
        for pid, spec in specs.items():
            cls, obs_space, act_space, overrides = (
                spec if isinstance(spec, tuple) else (None, None, None,
                                                      spec or {}))
            pconf = dict(config)
            pconf.update(overrides or {})
            self.policy_map[pid] = (cls or policy_cls)(
                obs_space or self.env.observation_space,
                act_space or self.env.action_space, pconf)
        # one shared connector pipeline pair at the env boundary (agents
        # are homogeneous here; per-policy pipelines would need per-policy
        # connector instances in the config)
        from ray_tpu.rllib.connectors import build_connectors
        self.obs_connectors, self.action_connectors = \
            build_connectors(config)
        self._obs, _ = self.env.reset(
            seed=(config.get("seed") or 0) * 10_000 + worker_index)
        self._eps_id = worker_index * 1_000_000
        self._episode_reward = 0.0
        self._episode_len = 0
        self._completed_rewards: List[float] = []
        self._completed_lens: List[int] = []

    @property
    def policy(self):
        """Single-policy accessor for code paths that expect one."""
        if "default_policy" in self.policy_map:
            return self.policy_map["default_policy"]
        return next(iter(self.policy_map.values()))

    def sample(self):
        from ray_tpu.rllib.sample_batch import MultiAgentBatch, SampleBatch
        frag_len = self.config.get("rollout_fragment_length", 200)
        explore = self.config.get("explore", True)
        # per-agent row buffers
        rows: Dict[Any, Dict[str, list]] = {}
        agent_pid: Dict[Any, str] = {}
        env_steps = 0
        for _ in range(frag_len):
            # group live agents by policy for batched forwards
            by_policy: Dict[str, List[Any]] = {}
            for aid in self._obs:
                pid = agent_pid.get(aid)
                if pid is None:
                    pid = self.policy_mapping_fn(aid)
                    agent_pid[aid] = pid
                by_policy.setdefault(pid, []).append(aid)
            actions: Dict[Any, Any] = {}
            proc_by_agent: Dict[Any, Any] = {}
            extras_by_agent: Dict[Any, Dict[str, Any]] = {}
            for pid, aids in by_policy.items():
                obs_arr = self.obs_connectors(
                    np.stack([self._obs[a] for a in aids]))
                acts, extras = self.policy_map[pid].compute_actions(
                    obs_arr, explore=explore)
                acts = self.action_connectors(acts)
                for i, aid in enumerate(aids):
                    actions[aid] = acts[i]
                    proc_by_agent[aid] = obs_arr[i]
                    extras_by_agent[aid] = {k: v[i]
                                            for k, v in extras.items()}
            next_obs, rews, terms, truncs, infos = self.env.step(actions)
            env_steps += 1
            for aid, act in actions.items():
                r = rows.setdefault(aid, {})
                done = bool(terms.get(aid, False))
                trunc = bool(truncs.get(aid, False))
                n_obs = next_obs.get(aid, self._obs[aid])
                if self.obs_connectors.connectors:
                    n_obs = self.obs_connectors.transform(
                        np.asarray(n_obs)[None])[0]
                vals = {
                    SampleBatch.OBS: proc_by_agent[aid],
                    SampleBatch.ACTIONS: act,
                    SampleBatch.REWARDS: np.float32(rews.get(aid, 0.0)),
                    SampleBatch.DONES: done,
                    SampleBatch.TRUNCATEDS: trunc,
                    SampleBatch.NEXT_OBS: n_obs,
                    SampleBatch.EPS_ID: np.int64(self._eps_id),
                    **extras_by_agent[aid],
                }
                for k, v in vals.items():
                    r.setdefault(k, []).append(v)
                self._episode_reward += float(rews.get(aid, 0.0))
            self._episode_len += 1
            if terms.get("__all__") or truncs.get("__all__"):
                self._completed_rewards.append(self._episode_reward)
                self._completed_lens.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._eps_id += 1
                self._obs, _ = self.env.reset()
                agent_pid.clear()
            else:
                self._obs = {a: o for a, o in next_obs.items()
                             if not (terms.get(a) or truncs.get(a))}
                if not self._obs:
                    # every agent individually finished without the env
                    # reporting __all__: still a completed episode
                    self._completed_rewards.append(self._episode_reward)
                    self._completed_lens.append(self._episode_len)
                    self._episode_reward = 0.0
                    self._episode_len = 0
                    self._eps_id += 1
                    self._obs, _ = self.env.reset()
                    agent_pid.clear()

        # per-agent trajectories -> policy postprocess -> per-policy concat
        per_policy: Dict[str, List[SampleBatch]] = {}
        for aid, cols in rows.items():
            pid = agent_pid.get(aid) or self.policy_mapping_fn(aid)
            b = SampleBatch({k: np.stack(v) if np.asarray(v[0]).ndim
                             else np.asarray(v)
                             for k, v in cols.items()})
            for ep in b.split_by_episode():
                per_policy.setdefault(pid, []).append(
                    self.policy_map[pid].postprocess_trajectory(ep))
        return MultiAgentBatch(
            {pid: SampleBatch.concat_samples(bs)
             for pid, bs in per_policy.items()}, env_steps)

    # ---- weights / metrics / state (WorkerSet-compatible surface) ----

    def get_weights(self):
        return {pid: p.get_weights() for pid, p in self.policy_map.items()}

    def set_weights(self, weights):
        for pid, w in weights.items():
            if pid in self.policy_map:
                self.policy_map[pid].set_weights(w)

    def get_metrics(self) -> Dict[str, Any]:
        out = {"episode_rewards": list(self._completed_rewards),
               "episode_lens": list(self._completed_lens)}
        self._completed_rewards = []
        self._completed_lens = []
        return out

    def set_exploration(self, **attrs):
        for p in self.policy_map.values():
            for k, v in attrs.items():
                setattr(p, k, v)

    def apply(self, fn, *args):
        return fn(self, *args)

    def get_policy_state(self):
        return {pid: p.get_state() for pid, p in self.policy_map.items()}

    def set_policy_state(self, state):
        for pid, s in state.items():
            if pid in self.policy_map:
                self.policy_map[pid].set_state(s)

    def evaluate_episodes(self, num_episodes: int) -> List[float]:
        """Greedy episodes; reward = sum over all agents."""
        from ray_tpu.rllib.env import make_env as _make
        env = _make(self.config["env"], self.config.get("env_config"))
        rewards = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=50_000 + self.worker_index * 1000 + ep)
            total, done = 0.0, False
            pid_of = {}
            while not done and obs:
                actions = {}
                for aid, ob in obs.items():
                    pid = pid_of.setdefault(aid,
                                            self.policy_mapping_fn(aid))
                    proc = self.obs_connectors.transform(
                        np.asarray(ob)[None])
                    a, _ = self.policy_map[pid].compute_actions(
                        proc, explore=False)
                    actions[aid] = self.action_connectors.transform(a)[0]
                obs, rews, terms, truncs, _ = env.step(actions)
                total += float(sum(rews.values()))
                done = bool(terms.get("__all__") or truncs.get("__all__"))
                obs = {a: o for a, o in obs.items()
                       if not (terms.get(a) or truncs.get(a))}
            rewards.append(total)
        return rewards

    def get_connector_state(self):
        return {"obs": self.obs_connectors.state(),
                "actions": self.action_connectors.state()}

    def set_connector_state(self, state):
        if not state:
            return
        self.obs_connectors.set_state(state.get("obs") or [])
        self.action_connectors.set_state(state.get("actions") or [])

    def ping(self) -> str:
        return "ok"

    def stop(self):
        pass
