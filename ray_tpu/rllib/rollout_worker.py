"""RolloutWorker + WorkerSet — sampling actors.

Reference analogue: rllib/evaluation/rollout_worker.py:153 (sample :856)
and worker_set.py:77 (sync_weights :381). TPU-first shape: the worker
steps a synchronous VectorEnv and runs ONE batched jitted policy forward
per env-step; fragments are cut at ``rollout_fragment_length`` and GAE is
computed worker-side so the learner only sees ready-to-train columns.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env import VectorEnv, make_env
from ray_tpu.rllib.sample_batch import SampleBatch


class RolloutWorker:
    """Samples experience from a vectorized env with a local policy copy."""

    def __init__(self, config: Dict[str, Any], policy_cls,
                 worker_index: int = 0):
        self.config = config
        self.worker_index = worker_index
        env_fn = lambda: make_env(config["env"], config.get("env_config"))
        self.vector_env = VectorEnv(
            env_fn, config.get("num_envs_per_worker", 1),
            seed=(config.get("seed") or 0) * 10_000 + worker_index)
        self.policy = policy_cls(
            self.vector_env.observation_space,
            self.vector_env.action_space, config)
        self._obs = self.vector_env.reset_all()
        n = self.vector_env.num_envs
        self._eps_ids = np.arange(n, dtype=np.int64) * 1_000_000 \
            + worker_index
        self._next_eps = self._eps_ids.max() + 1
        self._episode_rewards = np.zeros(n, np.float64)
        self._episode_lens = np.zeros(n, np.int64)
        self._completed_rewards: List[float] = []
        self._completed_lens: List[int] = []

    def sample(self) -> SampleBatch:
        """Collect ``rollout_fragment_length`` steps from every sub-env."""
        frag_len = self.config.get("rollout_fragment_length", 200)
        n_envs = self.vector_env.num_envs
        cols: Dict[str, list] = {
            k: [] for k in (SampleBatch.OBS, SampleBatch.ACTIONS,
                            SampleBatch.REWARDS, SampleBatch.DONES,
                            SampleBatch.TRUNCATEDS, SampleBatch.NEXT_OBS,
                            SampleBatch.EPS_ID)}
        explore = self.config.get("explore", True)
        for _ in range(frag_len):
            actions, extras = self.policy.compute_actions(
                self._obs, explore=explore)
            next_obs, rews, terms, truncs, infos = self.vector_env.step(
                actions)
            true_next = next_obs.copy()
            for i, info in enumerate(infos):
                if "terminal_observation" in info:
                    true_next[i] = info["terminal_observation"]
            cols[SampleBatch.OBS].append(self._obs.copy())
            cols[SampleBatch.ACTIONS].append(actions)
            cols[SampleBatch.REWARDS].append(rews)
            cols[SampleBatch.DONES].append(terms)
            cols[SampleBatch.TRUNCATEDS].append(truncs)
            cols[SampleBatch.NEXT_OBS].append(true_next)
            cols[SampleBatch.EPS_ID].append(self._eps_ids.copy())
            # every policy extra (logp, dist inputs, vf preds, algo-
            # specific columns like SAC's raw_actions) becomes a column
            for k, v in extras.items():
                cols.setdefault(k, []).append(v)
            self._episode_rewards += rews
            self._episode_lens += 1
            finished = terms | truncs
            for i in np.nonzero(finished)[0]:
                self._completed_rewards.append(
                    float(self._episode_rewards[i]))
                self._completed_lens.append(int(self._episode_lens[i]))
                self._episode_rewards[i] = 0.0
                self._episode_lens[i] = 0
                self._eps_ids[i] = self._next_eps
                self._next_eps += 1
            self._obs = next_obs

        # [T, N, ...] → per-env trajectories → policy postprocess (GAE
        # for PPO, no-op for DQN/IMPALA) → concat.
        stacked = {k: np.stack(v) for k, v in cols.items()}
        frags = []
        for i in range(n_envs):
            env_cols = SampleBatch(
                {k: v[:, i] for k, v in stacked.items()})
            for ep in env_cols.split_by_episode():
                frags.append(self.policy.postprocess_trajectory(ep))
        return SampleBatch.concat_samples(frags)

    def sample_with_count(self):
        b = self.sample()
        return b, b.count

    # ---- weights / metrics / state ----

    def get_weights(self):
        return self.policy.get_weights()

    def set_weights(self, weights):
        self.policy.set_weights(weights)

    def get_metrics(self) -> Dict[str, Any]:
        out = {
            "episode_rewards": list(self._completed_rewards),
            "episode_lens": list(self._completed_lens),
        }
        self._completed_rewards = []
        self._completed_lens = []
        return out

    def apply(self, fn, *args):
        """Run ``fn(policy, *args)`` on this worker's policy — used to
        propagate learner-side knobs (e.g. DQN epsilon) to remote actors."""
        return fn(self.policy, *args)

    def set_exploration(self, **attrs):
        for k, v in attrs.items():
            setattr(self.policy, k, v)

    def get_policy_state(self):
        return self.policy.get_state()

    def set_policy_state(self, state):
        self.policy.set_state(state)

    def ping(self) -> str:
        return "ok"

    def stop(self):
        pass


class WorkerSet:
    """Local learner worker + N remote rollout actors
    (reference: rllib/evaluation/worker_set.py:77)."""

    def __init__(self, config: Dict[str, Any], policy_cls,
                 num_workers: int):
        self.config = config
        self.policy_cls = policy_cls
        self.local_worker = RolloutWorker(config, policy_cls,
                                          worker_index=0)
        self.remote_workers: List[Any] = []
        if num_workers > 0:
            remote_cls = ray_tpu.remote(
                num_cpus=config.get("num_cpus_per_worker", 1))(RolloutWorker)
            self.remote_workers = [
                remote_cls.remote(config, policy_cls, worker_index=i + 1)
                for i in range(num_workers)]

    def sync_weights(self):
        """Broadcast learner weights via ONE object-store put
        (reference: worker_set.py:381 + ppo.py:345)."""
        if not self.remote_workers:
            return
        ref = ray_tpu.put(self.local_worker.get_weights())
        ray_tpu.get([w.set_weights.remote(ref)
                     for w in self.remote_workers])

    def set_exploration(self, **attrs):
        """Propagate exploration knobs (e.g. epsilon) to every policy copy,
        local and remote."""
        self.local_worker.set_exploration(**attrs)
        if self.remote_workers:
            ray_tpu.get([w.set_exploration.remote(**attrs)
                         for w in self.remote_workers])

    def sample_all(self) -> List[SampleBatch]:
        if not self.remote_workers:
            return [self.local_worker.sample()]
        return ray_tpu.get([w.sample.remote() for w in self.remote_workers])

    def collect_metrics(self) -> List[Dict[str, Any]]:
        out = [self.local_worker.get_metrics()]
        if self.remote_workers:
            out += ray_tpu.get(
                [w.get_metrics.remote() for w in self.remote_workers])
        return out

    def stop(self):
        for w in self.remote_workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass


def synchronous_parallel_sample(worker_set: WorkerSet,
                                max_env_steps: Optional[int] = None
                                ) -> SampleBatch:
    """Keep sampling rounds until ``max_env_steps`` collected
    (reference: rllib/execution/rollout_ops.py:21)."""
    batches: List[SampleBatch] = []
    steps = 0
    target = max_env_steps or 1
    while steps < target:
        round_batches = worker_set.sample_all()
        for b in round_batches:
            batches.append(b)
            steps += b.count
        if max_env_steps is None:
            break
    return SampleBatch.concat_samples(batches)
