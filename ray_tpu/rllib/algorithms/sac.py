"""SAC — soft actor-critic for continuous control.

Reference analogue: rllib/algorithms/sac/. Twin Q-networks, squashed
Gaussian policy, entropy temperature auto-tuning; the whole
actor+critic+alpha update is one jitted program over replayed batches.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import Box
from ray_tpu.rllib.replay_buffers import ReplayBuffer
from ray_tpu.rllib.rollout_worker import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import SampleBatch


class _SACNets(nn.Module):
    act_dim: int
    hidden: int = 256

    def setup(self):
        self.pi_net = nn.Sequential([
            nn.Dense(self.hidden), nn.relu,
            nn.Dense(self.hidden), nn.relu,
            nn.Dense(2 * self.act_dim)])
        self.q1_net = nn.Sequential([
            nn.Dense(self.hidden), nn.relu,
            nn.Dense(self.hidden), nn.relu, nn.Dense(1)])
        self.q2_net = nn.Sequential([
            nn.Dense(self.hidden), nn.relu,
            nn.Dense(self.hidden), nn.relu, nn.Dense(1)])

    def pi(self, obs):
        out = self.pi_net(obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        return mean, jnp.clip(log_std, -20.0, 2.0)

    def q(self, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        return self.q1_net(x)[..., 0], self.q2_net(x)[..., 0]

    def __call__(self, obs, act):
        # init-time wiring only
        return self.pi(obs), self.q(obs, act)


def _dataset_action_logp(acts, mean, log_std):
    """log π(a|s) of DATASET actions under a squashed Gaussian: invert
    the tanh, then apply the change-of-variables correction (shared by
    the offline algorithms CQL/CRR)."""
    pre = jnp.arctanh(jnp.clip(acts, -1.0 + 1e-6, 1.0 - 1e-6))
    std = jnp.exp(log_std)
    return jnp.sum(
        -0.5 * ((pre - mean) / std) ** 2 - log_std
        - 0.5 * jnp.log(2 * jnp.pi)
        - jnp.log(1 - acts ** 2 + 1e-6), axis=-1)


def _squash(mean, log_std, rng):
    std = jnp.exp(log_std)
    eps = jax.random.normal(rng, mean.shape)
    pre = mean + std * eps
    act = jnp.tanh(pre)
    # log-prob with tanh correction
    logp = jnp.sum(
        -0.5 * (eps ** 2) - log_std - 0.5 * jnp.log(2 * jnp.pi)
        - jnp.log(1 - act ** 2 + 1e-6), axis=-1)
    return act, logp


class SACPolicy:
    """Standalone policy (does not reuse JaxPolicy's single-net layout).
    Presents the same worker-facing API: compute_actions /
    postprocess_trajectory / get,set_weights."""

    def __init__(self, obs_space, action_space, config: Dict[str, Any]):
        assert isinstance(action_space, Box), "SAC is continuous-only"
        self.observation_space = obs_space
        self.action_space = action_space
        self.config = config
        self.act_dim = int(np.prod(action_space.shape))
        self.low = np.asarray(action_space.low, np.float32)
        self.high = np.asarray(action_space.high, np.float32)
        self.model = _SACNets(self.act_dim)
        self._rng = jax.random.PRNGKey(config.get("seed") or 0)
        obs_dim = obs_space.shape or (1,)
        dummy_o = jnp.zeros((1, *obs_dim), jnp.float32)
        dummy_a = jnp.zeros((1, self.act_dim), jnp.float32)
        self.params = self.model.init(self._next_rng(), dummy_o,
                                      dummy_a)["params"]
        self.target_params = jax.tree_util.tree_map(jnp.copy,
                                                    self.params)
        self.log_alpha = jnp.zeros(())
        self.optimizer = optax.adam(config.get("lr", 3e-4))
        self.opt_state = self.optimizer.init(
            (self.params, self.log_alpha))
        self._jit_act = jax.jit(self._act_impl)
        self._jit_update = jax.jit(self._update_impl)
        self.global_timestep = 0

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _act_impl(self, params, obs, rng, explore):
        mean, log_std = self.model.apply({"params": params}, obs,
                                         method=_SACNets.pi)
        stoch, _ = _squash(mean, log_std, rng)
        act = jnp.where(explore, stoch, jnp.tanh(mean))
        return act

    def compute_actions(self, obs, explore=True):
        act = np.asarray(self._jit_act(self.params, jnp.asarray(obs),
                                       self._next_rng(), explore))
        scaled = self.low + (act + 1.0) * 0.5 * (self.high - self.low)
        n = len(scaled)
        return scaled, {
            SampleBatch.ACTION_LOGP: np.zeros(n, np.float32),
            SampleBatch.ACTION_DIST_INPUTS: np.zeros(
                (n, 2 * self.act_dim), np.float32),
            SampleBatch.VF_PREDS: np.zeros(n, np.float32),
            "raw_actions": act,
        }

    def postprocess_trajectory(self, batch):
        return batch  # off-policy: no advantage computation

    def _update_impl(self, params, target_params, log_alpha, opt_state,
                     batch, rng):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        target_entropy = -float(self.act_dim)
        obs = batch[SampleBatch.OBS]
        nobs = batch[SampleBatch.NEXT_OBS]
        acts = batch["raw_actions"]
        rews = batch[SampleBatch.REWARDS]
        not_done = 1.0 - batch[SampleBatch.DONES].astype(jnp.float32)
        rng1, rng2 = jax.random.split(rng)

        # target Q
        mean_n, log_std_n = self.model.apply(
            {"params": target_params}, nobs, method=_SACNets.pi)
        next_a, next_logp = _squash(mean_n, log_std_n, rng1)
        tq1, tq2 = self.model.apply({"params": target_params}, nobs,
                                    next_a, method=_SACNets.q)
        alpha = jnp.exp(log_alpha)
        target_q = rews + gamma * not_done * (
            jnp.minimum(tq1, tq2) - alpha * next_logp)
        target_q = jax.lax.stop_gradient(target_q)

        def loss_fn(trainables):
            p, la = trainables
            q1, q2 = self.model.apply({"params": p}, obs, acts,
                                      method=_SACNets.q)
            critic_loss = jnp.mean((q1 - target_q) ** 2
                                   + (q2 - target_q) ** 2)
            mean, log_std = self.model.apply({"params": p}, obs,
                                             method=_SACNets.pi)
            new_a, new_logp = _squash(mean, log_std, rng2)
            # Actor term: gradient flows through the *action* into Q, but
            # must not touch the Q-network parameters (reference SAC uses
            # separate optimizers — sac_torch_policy.py optimizer_fn — so
            # actor gradients never push Q up for policy actions).
            frozen_p = jax.lax.stop_gradient(p)
            nq1, nq2 = self.model.apply({"params": frozen_p}, obs, new_a,
                                        method=_SACNets.q)
            actor_loss = jnp.mean(
                jnp.exp(jax.lax.stop_gradient(la)) * new_logp
                - jnp.minimum(nq1, nq2))
            alpha_loss = -jnp.mean(
                la * jax.lax.stop_gradient(new_logp + target_entropy))
            total = critic_loss + actor_loss + alpha_loss
            return total, {"critic_loss": critic_loss,
                           "actor_loss": actor_loss,
                           "alpha": jnp.exp(la),
                           "mean_q": jnp.mean(q1)}

        (loss_val, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)((params, log_alpha))
        updates, opt_state = self.optimizer.update(
            grads, opt_state, (params, log_alpha))
        params, log_alpha = optax.apply_updates((params, log_alpha),
                                                updates)
        tau = cfg.get("tau", 0.005)
        target_params = jax.tree_util.tree_map(
            lambda t, p: (1 - tau) * t + tau * p, target_params, params)
        stats = dict(stats)
        stats["total_loss"] = loss_val
        return params, target_params, log_alpha, opt_state, stats

    def learn_on_batch(self, batch) -> Dict[str, float]:
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                  if isinstance(v, np.ndarray) and v.dtype != object}
        (self.params, self.target_params, self.log_alpha,
         self.opt_state, stats) = self._jit_update(
            self.params, self.target_params, self.log_alpha,
            self.opt_state, jbatch, self._next_rng())
        self.global_timestep += batch.count
        return {k: float(v) for k, v in stats.items()}

    def value(self, obs):
        return np.zeros(len(obs), np.float32)

    def get_weights(self):
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def get_state(self):
        return {"weights": self.get_weights(),
                "target": jax.device_get(self.target_params),
                "log_alpha": float(self.log_alpha),
                "opt_state": jax.device_get(self.opt_state),
                "global_timestep": self.global_timestep}

    def set_state(self, state):
        self.set_weights(state["weights"])
        self.target_params = jax.tree_util.tree_map(
            jnp.asarray, state["target"])
        self.log_alpha = jnp.asarray(state["log_alpha"])
        self.opt_state = jax.tree_util.tree_map(
            jnp.asarray, state["opt_state"],
            is_leaf=lambda x: isinstance(x, (np.ndarray, np.generic)))
        self.global_timestep = state.get("global_timestep", 0)


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self._config.update({
            "lr": 3e-4, "tau": 0.005,
            "replay_buffer_capacity": 100_000,
            "learning_starts": 256,
            "train_batch_size": 256,
            "rollout_fragment_length": 1,
            "training_intensity": 1,
        })


class SAC(Algorithm):
    _policy_cls = SACPolicy
    _default_config_cls = SACConfig

    def setup(self, config):
        super().setup(config)
        self.replay = ReplayBuffer(
            self.config["replay_buffer_capacity"],
            seed=self.config.get("seed"))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        policy = self.workers.local_worker.policy
        batch = synchronous_parallel_sample(self.workers)
        self._timesteps_total += batch.count
        self.replay.add(batch)
        stats: Dict[str, float] = {}
        if len(self.replay) >= cfg["learning_starts"]:
            for _ in range(max(1, cfg.get("training_intensity", 1))):
                stats = policy.learn_on_batch(
                    self.replay.sample(cfg["train_batch_size"]))
            self.workers.sync_weights()
        return {"num_env_steps_sampled_this_iter": batch.count,
                "replay_size": len(self.replay),
                **{f"learner/{k}": v for k, v in stats.items()}}
