"""DQN — deep Q-learning with target network, double-Q, and
(optionally prioritized) replay.

Reference analogue: rllib/algorithms/dqn/dqn.py + dqn_torch_policy.py.
The TD-error/update is one jitted program; the target network is a second
param pytree synced by period (pure copy, no graph surgery).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.replay_buffers import (PrioritizedReplayBuffer,
                                          ReplayBuffer)
from ray_tpu.rllib.rollout_worker import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import SampleBatch


class DQNPolicy(JaxPolicy):
    """Q-network policy: logits head doubles as Q-values; epsilon-greedy
    exploration handled host-side via ``exploration_epsilon``."""

    def __init__(self, obs_space, action_space, config):
        super().__init__(obs_space, action_space, config)
        assert self.discrete, "DQN requires a discrete action space"
        self.target_params = jax.tree_util.tree_map(
            jnp.copy, self.params)
        self.exploration_epsilon = config.get("initial_epsilon", 1.0)
        self._np_rng = np.random.default_rng(config.get("seed"))

    def compute_actions(self, obs, explore=True):
        actions, extras = super().compute_actions(obs, explore=False)
        if explore:
            n = len(actions)
            rand = self._np_rng.random(n)
            random_actions = self._np_rng.integers(self.action_space.n,
                                                   size=n)
            actions = np.where(rand < self.exploration_epsilon,
                               random_actions, actions)
        return actions, extras

    def loss(self, params, batch):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        q, _ = self.model.apply({"params": params},
                                batch[SampleBatch.OBS])
        q_sel = jnp.take_along_axis(
            q, batch[SampleBatch.ACTIONS][..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        # Target params ride inside ``batch`` so they are real jit
        # arguments — a captured attribute would be baked in as a
        # compile-time constant and target syncs would be ignored.
        q_next_target, _ = self.model.apply(
            {"params": batch["_target_params"]},
            batch[SampleBatch.NEXT_OBS])
        if cfg.get("double_q", True):
            q_next_online, _ = self.model.apply(
                {"params": params}, batch[SampleBatch.NEXT_OBS])
            best = jnp.argmax(q_next_online, axis=-1)
        else:
            best = jnp.argmax(q_next_target, axis=-1)
        q_next = jnp.take_along_axis(
            q_next_target, best[..., None], axis=-1)[..., 0]
        not_done = 1.0 - batch[SampleBatch.DONES].astype(jnp.float32)
        target = batch[SampleBatch.REWARDS] + gamma * not_done * q_next
        td_error = q_sel - jax.lax.stop_gradient(target)
        weights = batch.get("weights", jnp.ones_like(td_error))
        loss = jnp.mean(weights * jnp.square(td_error))
        return loss, {"mean_q": jnp.mean(q_sel),
                      "mean_td_error": jnp.mean(jnp.abs(td_error)),
                      "td_error_max": jnp.max(jnp.abs(td_error)),
                      # per-sample |TD| (array) for prioritized replay
                      "td_errors": jnp.abs(td_error)}

    def learn_on_batch(self, batch):
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                  if isinstance(v, np.ndarray) and v.dtype != object}
        jbatch["_target_params"] = self.target_params
        self.params, self.opt_state, stats = self._jit_update(
            self.params, self.opt_state, jbatch)
        self.global_timestep += batch.count
        from ray_tpu.rllib.policy import _stats_to_host
        return _stats_to_host(stats)

    def compute_td_errors(self, batch: SampleBatch) -> float:
        """Host-visible |TD| for priority updates."""
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                  if isinstance(v, np.ndarray) and v.dtype != object}
        jbatch["_target_params"] = self.target_params
        _, stats = self.loss(self.params, jbatch)
        return float(stats["mean_td_error"])

    def update_target(self):
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self._config.update({
            "lr": 5e-4,
            "replay_buffer_capacity": 50_000,
            "prioritized_replay": False,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
            "learning_starts": 1000,
            "train_batch_size": 32,
            "rollout_fragment_length": 4,
            "target_network_update_freq": 500,
            "initial_epsilon": 1.0,
            "final_epsilon": 0.02,
            "epsilon_timesteps": 10_000,
            "double_q": True,
            "num_steps_sampled_before_learning": 1000,
            "training_intensity": 1,
        })


class DQN(Algorithm):
    _policy_cls = DQNPolicy
    _default_config_cls = DQNConfig

    def setup(self, config):
        super().setup(config)
        cfg = self.config
        if cfg.get("prioritized_replay"):
            self.replay = PrioritizedReplayBuffer(
                cfg["replay_buffer_capacity"],
                alpha=cfg["prioritized_replay_alpha"],
                seed=cfg.get("seed"))
        else:
            self.replay = ReplayBuffer(cfg["replay_buffer_capacity"],
                                       seed=cfg.get("seed"))
        self._steps_since_target_sync = 0

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._timesteps_total
                   / max(1, cfg["epsilon_timesteps"]))
        return cfg["initial_epsilon"] + frac * (
            cfg["final_epsilon"] - cfg["initial_epsilon"])

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        policy = self.workers.local_worker.policy
        # epsilon must reach every sampling policy copy, incl. remote
        self.workers.set_exploration(
            exploration_epsilon=self._epsilon())
        batch = synchronous_parallel_sample(self.workers)
        self._timesteps_total += batch.count
        self.replay.add(batch)
        stats: Dict[str, float] = {}
        if len(self.replay) >= cfg["learning_starts"]:
            for _ in range(max(1, cfg.get("training_intensity", 1))):
                if cfg.get("prioritized_replay"):
                    train = self.replay.sample(
                        cfg["train_batch_size"],
                        beta=cfg["prioritized_replay_beta"])
                else:
                    train = self.replay.sample(cfg["train_batch_size"])
                stats = policy.learn_on_batch(train)
                if cfg.get("prioritized_replay"):
                    self.replay.update_priorities(
                        train["batch_indexes"],
                        stats.pop("td_errors"))
            self._steps_since_target_sync += batch.count
            if (self._steps_since_target_sync
                    >= cfg["target_network_update_freq"]):
                policy.update_target()
                self._steps_since_target_sync = 0
            self.workers.sync_weights()
        stats.pop("td_errors", None)
        return {
            "num_env_steps_sampled_this_iter": batch.count,
            "epsilon": policy.exploration_epsilon,
            "replay_size": len(self.replay),
            **{f"learner/{k}": v for k, v in stats.items()},
        }
