"""SimpleQ — deep Q-learning without the DQN extras.

Reference analogue: rllib/algorithms/simple_q/ (simple_q.py,
simple_q_torch_policy.py): plain TD(0) target from a periodically
synced target network — no double-Q, no prioritized replay, no
n-step returns. All machinery is shared with DQN (dqn.py); this
config pins the extras off, matching the reference's relationship
where DQN extends SimpleQ (here inverted: the featureful class is
the base and SimpleQ is the subtraction).
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig


class SimpleQConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SimpleQ)
        self._config.update({
            "double_q": False,
            "prioritized_replay": False,
            "lr": 5e-4,
            "train_batch_size": 32,
            "target_network_update_freq": 500,
        })


class SimpleQ(DQN):
    _default_config_cls = SimpleQConfig
