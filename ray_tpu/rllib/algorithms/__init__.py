from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig

__all__ = ["PPO", "PPOConfig", "DQN", "DQNConfig", "IMPALA",
           "IMPALAConfig"]
