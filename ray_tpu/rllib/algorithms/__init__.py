from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.pg import A2C, A2CConfig, PG, PGConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.bc import (BC, BCConfig, MARWIL,
                                         MARWILConfig)

__all__ = ["PPO", "PPOConfig", "DQN", "DQNConfig", "IMPALA",
           "IMPALAConfig", "PG", "PGConfig", "A2C", "A2CConfig",
           "SAC", "SACConfig", "BC", "BCConfig", "MARWIL",
           "MARWILConfig"]
