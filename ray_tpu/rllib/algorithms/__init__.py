from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.ddppo import DDPPO, DDPPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.simple_q import SimpleQ, SimpleQConfig
from ray_tpu.rllib.algorithms.apex_dqn import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.algorithms.apex_ddpg import ApexDDPG, ApexDDPGConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.pg import A2C, A2CConfig, PG, PGConfig
from ray_tpu.rllib.algorithms.a3c import A3C, A3CConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.ddpg import (DDPG, DDPGConfig, TD3,
                                           TD3Config)
from ray_tpu.rllib.algorithms.bc import (BC, BCConfig, MARWIL,
                                         MARWILConfig)
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.crr import CRR, CRRConfig
from ray_tpu.rllib.algorithms.es import ARS, ARSConfig, ES, ESConfig
from ray_tpu.rllib.algorithms.qmix import QMix, QMixConfig
from ray_tpu.rllib.algorithms.r2d2 import R2D2, R2D2Config
from ray_tpu.rllib.algorithms.dt import DT, DTConfig
from ray_tpu.rllib.algorithms.maddpg import MADDPG, MADDPGConfig
from ray_tpu.rllib.algorithms.bandit import (BanditLinTS,
                                             BanditLinTSConfig,
                                             BanditLinUCB,
                                             BanditLinUCBConfig)
from ray_tpu.rllib.algorithms.alpha_star import (AlphaStar,
                                                 AlphaStarConfig)
from ray_tpu.rllib.algorithms.alpha_zero import (AlphaZero,
                                                 AlphaZeroConfig)
from ray_tpu.rllib.algorithms.dreamer import Dreamer, DreamerConfig
from ray_tpu.rllib.algorithms.maml import MAML, MAMLConfig
from ray_tpu.rllib.algorithms.mbmpo import MBMPO, MBMPOConfig
from ray_tpu.rllib.algorithms.slateq import SlateQ, SlateQConfig

__all__ = ["PPO", "PPOConfig", "DDPPO", "DDPPOConfig", "DQN",
           "DQNConfig", "SimpleQ", "SimpleQConfig", "ApexDQN",
           "ApexDQNConfig", "ApexDDPG", "ApexDDPGConfig",
           "IMPALA", "IMPALAConfig", "APPO",
           "APPOConfig", "PG", "PGConfig",
           "A2C", "A2CConfig", "A3C", "A3CConfig",
           "SAC", "SACConfig", "DDPG", "DDPGConfig",
           "TD3", "TD3Config", "BC", "BCConfig", "MARWIL",
           "MARWILConfig", "CQL", "CQLConfig", "CRR", "CRRConfig",
           "ES", "ESConfig", "ARS", "ARSConfig",
           "BanditLinUCB", "BanditLinUCBConfig",
           "BanditLinTS", "BanditLinTSConfig",
           "QMix", "QMixConfig", "R2D2", "R2D2Config", "DT", "DTConfig",
           "MADDPG", "MADDPGConfig",
           "AlphaStar", "AlphaStarConfig",
           "AlphaZero", "AlphaZeroConfig", "Dreamer", "DreamerConfig",
           "MAML", "MAMLConfig", "MBMPO", "MBMPOConfig",
           "SlateQ", "SlateQConfig"]
