from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.apex_dqn import ApexDQN, ApexDQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.pg import A2C, A2CConfig, PG, PGConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.ddpg import (DDPG, DDPGConfig, TD3,
                                           TD3Config)
from ray_tpu.rllib.algorithms.bc import (BC, BCConfig, MARWIL,
                                         MARWILConfig)

__all__ = ["PPO", "PPOConfig", "DQN", "DQNConfig", "ApexDQN",
           "ApexDQNConfig", "IMPALA", "IMPALAConfig", "APPO",
           "APPOConfig", "PG", "PGConfig",
           "A2C", "A2CConfig", "SAC", "SACConfig", "DDPG", "DDPGConfig",
           "TD3", "TD3Config", "BC", "BCConfig", "MARWIL",
           "MARWILConfig"]
