"""MBMPO — model-based meta-policy optimization.

Reference analogue: rllib/algorithms/mbmpo/ (mbmpo.py,
model_ensemble.py; Clavera et al. 2018): learn an ENSEMBLE of dynamics
models from real transitions, then treat each ensemble member as a
MAML "task" — the policy is meta-trained so that one inner
policy-gradient step on imagined rollouts from any single model yields
a good policy, which makes the meta-policy robust to model bias.

TPU-first design: the whole imagination pipeline is one jitted
program — ``lax.scan`` unrolls E parallel imagined episodes through
the learned dynamics (policy step → model step → known reward), and
the meta-gradient differentiates through the inner adaptation exactly
as in MAML (second-order terms included).  Dynamics training is
vmapped over the ensemble so all K models fit in one XLA program.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm
from ray_tpu.rllib.algorithms.maml import PointGoalEnv, _GaussianPolicy


class _DynamicsModel(nn.Module):
    """Predicts the state delta for (obs, act)."""
    obs_dim: int
    hidden: int = 64

    @nn.compact
    def __call__(self, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.obs_dim)(x)


class MBMPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MBMPO)
        self._config.update({
            "env": "point_goal",
            "env_config": {},
            "ensemble_size": 4,
            "model_lr": 1e-3,
            "model_train_iters": 60,
            "real_episodes_per_iter": 16,
            "imagined_episodes": 16,
            "horizon": 20,
            "inner_lr": 0.1,
            "lr": 1e-3,              # meta (outer) lr
            "inner_adaptation_steps": 1,
            "hidden": 64,
            "buffer_size": 4000,
        })


class MBMPO(LocalAlgorithm):
    """Model-based MAML: ensemble members are the task distribution
    (reference: mbmpo.py training_step — fit models on real data,
    inner-adapt on imagined data per model, meta-update through the
    adaptation)."""

    _default_config_cls = MBMPOConfig

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        if cfg["env"] != "point_goal":
            raise ValueError("MBMPO ships the point_goal dynamics family")
        env_cfg = dict(cfg.get("env_config") or {})
        env_cfg.setdefault("horizon", cfg["horizon"])
        self.env = PointGoalEnv(env_cfg)
        self.env.set_task(np.array([1.0, 0.0], np.float32))  # fixed task
        self.obs_dim, self.act_dim = 2, 2
        self.policy = _GaussianPolicy(self.act_dim, cfg["hidden"])
        self.model = _DynamicsModel(self.obs_dim, cfg["hidden"])
        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        k1, k2 = jax.random.split(self._rng)
        self.params = self.policy.init(
            k1, jnp.zeros((1, self.obs_dim)))["params"]
        self.target_params = self.params  # checkpoint symmetry
        # ensemble init: one vmapped param tree, K leading dim
        K = cfg["ensemble_size"]
        keys = jax.random.split(k2, K)
        self.model_params = jax.vmap(
            lambda k: self.model.init(
                k, jnp.zeros((1, self.obs_dim)),
                jnp.zeros((1, self.act_dim)))["params"])(keys)
        self.optimizer = optax.adam(cfg["lr"])
        self.opt_state = self.optimizer.init(self.params)
        self.model_opt = optax.adam(cfg["model_lr"])
        self.model_opt_state = self.model_opt.init(self.model_params)
        self._buf_obs = np.zeros((0, self.obs_dim), np.float32)
        self._buf_act = np.zeros((0, self.act_dim), np.float32)
        self._buf_next = np.zeros((0, self.obs_dim), np.float32)

        def act_impl(params, obs, key):
            mean, logstd = self.policy.apply({"params": params}, obs)
            eps = jax.random.normal(key, mean.shape)
            return mean + jnp.exp(logstd) * eps

        self._jit_act = jax.jit(act_impl)
        self._jit_model_update = jax.jit(self._model_update_impl)
        self._jit_meta = jax.jit(self._meta_impl)
        self._jit_adapt = jax.jit(self._adapt_impl)
        self._jit_imagine = jax.jit(self._imagine_impl)
        self._init_local_state()

    # ---- dynamics ensemble ----

    def _model_loss(self, mparams, obs, act, nxt):
        # vmapped over the ensemble: each member sees its own bootstrap
        pred = jax.vmap(
            lambda p, o, a: self.model.apply({"params": p}, o, a)
        )(mparams, obs, act)
        return jnp.mean((pred - (nxt - obs)) ** 2)

    def _model_update_impl(self, mparams, mopt, obs, act, nxt):
        loss, grads = jax.value_and_grad(self._model_loss)(
            mparams, obs, act, nxt)
        updates, mopt = self.model_opt.update(grads, mopt, mparams)
        return optax.apply_updates(mparams, updates), mopt, loss

    # ---- imagination (pure jax, one scan per rollout batch) ----

    def _imagine_impl(self, policy_params, model_params_k, key):
        """E imagined episodes of length T under ONE ensemble member.
        Returns a REINFORCE batch (obs/actions/advantages)."""
        cfg = self.config
        E, T = cfg["imagined_episodes"], cfg["horizon"]
        goal = jnp.asarray(self.env.goal)
        obs0 = jnp.zeros((E, self.obs_dim))

        def step(carry, key):
            obs = carry
            mean, logstd = self.policy.apply({"params": policy_params}, obs)
            act = mean + jnp.exp(logstd) * jax.random.normal(
                key, mean.shape)
            act = jnp.clip(act, -1.0, 1.0)
            delta = self.model.apply({"params": model_params_k}, obs, act)
            nxt = jnp.clip(obs + delta, -2.0, 2.0)
            r = -jnp.linalg.norm(nxt - goal[None], axis=-1)
            return nxt, (obs, act, r)

        keys = jax.random.split(key, T)
        _, (obs, act, rew) = jax.lax.scan(step, obs0, keys)  # (T, E, ·)
        rtg = jnp.cumsum(rew[::-1], axis=0)[::-1]            # (T, E)
        adv = rtg - rtg.mean(axis=1, keepdims=True)          # per-t baseline
        return {"obs": obs.reshape(-1, self.obs_dim),
                "actions": act.reshape(-1, self.act_dim),
                "advantages": adv.reshape(-1)}, jnp.mean(
                    jnp.sum(rew, axis=0))

    # ---- MAML machinery over ensemble members ----

    def _logp(self, params, obs, act):
        mean, logstd = self.policy.apply({"params": params}, obs)
        var = jnp.exp(2 * logstd)
        return jnp.sum(-0.5 * ((act - mean) ** 2 / var) - logstd
                       - 0.5 * jnp.log(2 * jnp.pi), axis=-1)

    def _surrogate(self, params, batch):
        adv = batch["advantages"]
        adv = adv / (jnp.std(adv) + 1e-6)
        return -jnp.mean(
            self._logp(params, batch["obs"], batch["actions"]) * adv)

    def _adapt_impl(self, params, batch):
        lr = self.config["inner_lr"]
        for _ in range(self.config["inner_adaptation_steps"]):
            grads = jax.grad(self._surrogate)(params, batch)
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)
        return params

    def _meta_impl(self, params, opt_state, pre_batches, post_batches):
        def outer_loss(p):
            losses = [
                self._surrogate(self._adapt_impl(p, pre), post)
                for pre, post in zip(pre_batches, post_batches)]
            return jnp.mean(jnp.stack(losses))

        loss, grads = jax.value_and_grad(outer_loss)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        return (optax.apply_updates(params, updates), opt_state,
                {"meta_loss": loss,
                 "grad_norm": optax.global_norm(grads)})

    # ---- real-env interaction ----

    def _next_key(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _collect_real(self, params, episodes) -> float:
        rewards = []
        for _ in range(episodes):
            obs, _ = self.env.reset()
            total, done = 0.0, False
            while not done:
                a = np.asarray(self._jit_act(
                    params, jnp.asarray(obs[None]), self._next_key()))[0]
                a = np.clip(a, -1.0, 1.0)
                nobs, r, term, trunc, _ = self.env.step(a)
                self._buf_obs = np.concatenate(
                    [self._buf_obs, obs[None]])[-self.config["buffer_size"]:]
                self._buf_act = np.concatenate(
                    [self._buf_act, a[None]])[-self.config["buffer_size"]:]
                self._buf_next = np.concatenate(
                    [self._buf_next, nobs[None]])[
                        -self.config["buffer_size"]:]
                total += r
                obs, done = nobs, (term or trunc)
            rewards.append(total)
        return float(np.mean(rewards))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        # the DEPLOYED policy is the adapted one (reference: MBMPO's
        # inner-adapted policies collect the next round of real data) —
        # adapt on imagination from one ensemble member once models exist
        deploy = self.params
        if len(self._buf_obs) > 0:
            mp0 = jax.tree_util.tree_map(lambda x: x[0], self.model_params)
            pre0, _ = self._jit_imagine(self.params, mp0, self._next_key())
            deploy = self._jit_adapt(self.params, pre0)
        real_reward = self._collect_real(deploy,
                                         cfg["real_episodes_per_iter"])
        # fit the ensemble on the buffer (bootstrap resample per member)
        n = len(self._buf_obs)
        rng = self._np_rng
        K = cfg["ensemble_size"]
        model_loss = 0.0
        for _ in range(cfg["model_train_iters"]):
            idx = rng.integers(0, n, size=(K, min(n, 256)))
            self.model_params, self.model_opt_state, model_loss = \
                self._jit_model_update(
                    self.model_params, self.model_opt_state,
                    jnp.asarray(self._buf_obs[idx]),
                    jnp.asarray(self._buf_act[idx]),
                    jnp.asarray(self._buf_next[idx]))
        # each ensemble member is one MAML task
        member = lambda k: jax.tree_util.tree_map(  # noqa: E731
            lambda x: x[k], self.model_params)
        pre_batches, post_batches, imag_rewards = [], [], []
        for k in range(K):
            mp = member(k)
            pre, _ = self._jit_imagine(self.params, mp, self._next_key())
            adapted = self._jit_adapt(self.params, pre)
            post, im_rw = self._jit_imagine(adapted, mp, self._next_key())
            pre_batches.append(pre)
            post_batches.append(post)
            imag_rewards.append(float(im_rw))
        self.params, self.opt_state, jstats = self._jit_meta(
            self.params, self.opt_state, pre_batches, post_batches)
        steps = cfg["real_episodes_per_iter"] * cfg["horizon"]
        self._timesteps_total += steps
        self._episode_reward_window.append(real_reward)
        return {
            "num_env_steps_sampled_this_iter": steps,
            "real_reward_mean": real_reward,
            "imagined_reward_mean": float(np.mean(imag_rewards)),
            "model_loss": float(model_loss),
            **{f"learner/{k}": float(v) for k, v in jstats.items()},
        }
