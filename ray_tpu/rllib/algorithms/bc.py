"""BC / MARWIL — offline imitation and advantage-weighted learning.

Reference analogue: rllib/algorithms/bc/ and rllib/algorithms/marwil/
(BC is MARWIL with beta=0). Trains from JsonReader datasets: no env
interaction for learning; an env may still be configured for
evaluation.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.offline import JsonReader
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.sample_batch import SampleBatch


class MARWILPolicy(JaxPolicy):
    def loss(self, params, batch):
        beta = self.config.get("beta", 1.0)
        dist_inputs, vf = self.model.apply(
            {"params": params}, batch[SampleBatch.OBS])
        logp = self.dist_logp(dist_inputs, batch[SampleBatch.ACTIONS])
        if beta > 0:
            # advantage = monte-carlo return - value prediction
            returns = batch["returns"]
            adv = returns - vf
            vf_loss = jnp.mean(adv ** 2)
            import jax as _jax
            norm_adv = _jax.lax.stop_gradient(
                jnp.clip((adv - adv.mean()) / (adv.std() + 1e-8),
                         -5.0, 5.0))
            weights = jnp.minimum(jnp.exp(beta * norm_adv), 20.0)
            imitation = -jnp.mean(weights * logp)
            total = imitation + self.config.get(
                "vf_coeff", 1.0) * vf_loss
            return total, {"imitation_loss": imitation,
                           "vf_loss": vf_loss,
                           "mean_weight": jnp.mean(weights)}
        imitation = -jnp.mean(logp)
        return imitation, {"imitation_loss": imitation}


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self._config.update({
            "lr": 1e-4, "beta": 1.0, "vf_coeff": 1.0,
            "input_path": None, "train_batch_size": 256,
            "num_iters_per_step": 10,
        })

    def offline_data(self, *, input_path=None, **kw):
        if input_path is not None:
            self._config["input_path"] = input_path
        self._config.update(kw)
        return self


class MARWIL(Algorithm):
    _policy_cls = MARWILPolicy
    _default_config_cls = MARWILConfig

    def setup(self, config):
        super().setup(config)
        path = self.config.get("input_path")
        if not path:
            raise ValueError("MARWIL/BC needs config['input_path']")
        self._data = JsonReader(path).read_all()
        # precompute per-row monte-carlo returns for the vf baseline
        gamma = self.config.get("gamma", 0.99)
        returns = np.zeros(self._data.count, np.float32)
        acc = 0.0
        rews = np.asarray(self._data[SampleBatch.REWARDS], np.float32)
        dones = np.asarray(self._data[SampleBatch.DONES], bool)
        for t in range(self._data.count - 1, -1, -1):
            if dones[t]:
                acc = 0.0
            acc = rews[t] + gamma * acc
            returns[t] = acc
        self._data["returns"] = returns
        self._rng = np.random.default_rng(self.config.get("seed"))

    def training_step(self) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        bs = self.config["train_batch_size"]
        stats: Dict[str, float] = {}
        for _ in range(self.config.get("num_iters_per_step", 10)):
            idx = self._rng.integers(self._data.count, size=bs)
            minibatch = SampleBatch(
                {k: np.asarray(v)[idx] for k, v in self._data.items()})
            stats = policy.learn_on_batch(minibatch)
            self._timesteps_total += bs
        self.workers.sync_weights()
        return {"num_env_steps_sampled_this_iter": 0,
                **{f"learner/{k}": v for k, v in stats.items()}}


class BCConfig(MARWILConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self._config["beta"] = 0.0  # pure imitation


class BC(MARWIL):
    _default_config_cls = BCConfig
