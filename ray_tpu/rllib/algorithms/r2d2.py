"""R2D2 — recurrent replay distributed DQN (Kapturowski et al. 2019).

Reference analogue: rllib/algorithms/r2d2/ (r2d2.py,
r2d2_torch_policy.py): an LSTM Q-network trained on replayed
SEQUENCES with burn-in — the first ``burn_in`` steps of each sampled
sequence only warm up the recurrent state (no gradient), the remainder
takes double-Q TD loss against a target network. This implementation
uses the paper's zero-start-state + burn-in strategy and stores whole
episodes in a sequence replay buffer.

TPU-first: the LSTM unroll is a ``flax.linen.scan`` over time inside
ONE jitted update — fixed (B, T) shapes, no per-step Python. Acting
threads the recurrent state explicitly (functional carry, no hidden
module state), so the collector is an ordinary host loop.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm
from ray_tpu.rllib.env import Discrete, make_env


class _RecurrentQNet(nn.Module):
    """Dense → LSTM → Q-head; call modes: ``step`` (one env step with
    carry) and ``unroll`` (scan over a (B, T) sequence)."""

    num_actions: int
    hidden: int = 64
    lstm_size: int = 64

    def setup(self):
        self.enc = nn.Dense(self.hidden)
        self.cell = nn.OptimizedLSTMCell(self.lstm_size)
        self.head = nn.Dense(self.num_actions)

    def step(self, carry, obs):
        x = nn.relu(self.enc(obs))
        carry, y = self.cell(carry, x)
        return carry, self.head(y)

    def unroll(self, carry, obs_seq):
        """obs_seq (B, T, do) → (carry, Q (B, T, A))."""
        x = nn.relu(self.enc(obs_seq))

        def body(cell, c, xt):
            return cell(c, xt)

        scan = nn.transforms.scan(
            body, variable_broadcast="params", split_rngs={"params": False},
            in_axes=1, out_axes=1)
        carry, y = scan(self.cell, carry, x)
        return carry, self.head(y)

    def __call__(self, obs_seq):  # init-time wiring
        carry = zero_carry(obs_seq.shape[0], self.lstm_size)
        return self.unroll(carry, obs_seq)


def zero_carry(batch: int, lstm_size: int):
    """LSTM (c, h) zero state — the paper's zero-start-state strategy;
    burn-in warms it up before the loss applies."""
    return (jnp.zeros((batch, lstm_size)), jnp.zeros((batch, lstm_size)))


class _SequenceReplay:
    """Episode store sampling fixed-length subsequences with a
    validity mask (short episodes are zero-padded)."""

    def __init__(self, capacity_episodes: int, seq_len: int, seed=None):
        self.capacity = capacity_episodes
        self.seq_len = seq_len
        self._episodes: List[Dict[str, np.ndarray]] = []
        self._rng = np.random.default_rng(seed)
        self.num_steps = 0

    def add_episode(self, ep: Dict[str, np.ndarray]):
        self._episodes.append(ep)
        self.num_steps += len(ep["rewards"])
        while len(self._episodes) > self.capacity:
            old = self._episodes.pop(0)
            self.num_steps -= len(old["rewards"])

    def sample(self, batch: int) -> Dict[str, np.ndarray]:
        T = self.seq_len
        out: Dict[str, list] = {k: [] for k in
                                ("obs", "actions", "rewards", "dones",
                                 "next_obs", "mask")}
        for _ in range(batch):
            ep = self._episodes[self._rng.integers(len(self._episodes))]
            n = len(ep["rewards"])
            start = int(self._rng.integers(0, max(1, n - T + 1)))
            end = min(start + T, n)
            pad = T - (end - start)

            def cut(key, feat_shape):
                seq = ep[key][start:end]
                if pad:
                    seq = np.concatenate(
                        [seq, np.zeros((pad, *feat_shape),
                                       seq.dtype)], axis=0)
                return seq

            do = ep["obs"].shape[1:]
            out["obs"].append(cut("obs", do))
            out["next_obs"].append(cut("next_obs", do))
            out["actions"].append(cut("actions", ()))
            out["rewards"].append(cut("rewards", ()))
            out["dones"].append(cut("dones", ()))
            m = np.zeros(T, np.float32)
            m[:end - start] = 1.0
            out["mask"].append(m)
        return {k: np.stack(v) for k, v in out.items()}


class R2D2Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or R2D2)
        self._config.update({
            "lr": 5e-4,
            "lstm_size": 64,
            "agent_hidden": 64,
            "double_q": True,
            "seq_len": 20,
            "burn_in": 4,
            "replay_capacity_episodes": 500,
            "learning_starts": 500,   # env steps
            "train_batch_size": 32,   # sequences per update
            "rollout_fragment_length": 64,
            "target_network_update_freq": 300,
            "initial_epsilon": 1.0,
            "final_epsilon": 0.05,
            "epsilon_timesteps": 5_000,
            "training_intensity": 4,
        })


class R2D2(LocalAlgorithm):
    _default_config_cls = R2D2Config

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        self.env = make_env(cfg["env"], cfg.get("env_config"))
        if not isinstance(self.env.action_space, Discrete):
            raise ValueError("R2D2 is discrete-action only")
        self.n_actions = self.env.action_space.n
        self.obs_dim = int(np.prod(self.env.observation_space.shape))

        self.qnet = _RecurrentQNet(self.n_actions, cfg["agent_hidden"],
                                   cfg["lstm_size"])
        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        dummy = jnp.zeros((1, cfg["seq_len"], self.obs_dim))
        self.params = self.qnet.init(self._next_rng(), dummy)["params"]
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(10.0), optax.adam(cfg["lr"]))
        self.opt_state = self.optimizer.init(self.params)
        self._jit_step = jax.jit(self._step_impl)
        self._jit_update = jax.jit(self._update_impl)

        self.replay = _SequenceReplay(cfg["replay_capacity_episodes"],
                                      cfg["seq_len"], cfg.get("seed"))
        self._init_local_state()
        self._reset_episode(seed=cfg.get("seed"))

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _reset_episode(self, seed=None):
        self._obs, _ = self.env.reset(seed=seed)
        self._carry = zero_carry(1, self.config["lstm_size"])
        self._ep_rows: Dict[str, list] = {k: [] for k in
                                          ("obs", "actions", "rewards",
                                           "dones", "next_obs")}
        self._ep_reward = 0.0

    # ---- jitted programs ----

    def _step_impl(self, params, carry, obs):
        return self.qnet.apply({"params": params}, carry, obs,
                               method=_RecurrentQNet.step)

    def _update_impl(self, params, target_params, opt_state, batch):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        burn = cfg["burn_in"]
        obs = batch["obs"]          # (B, T, do)
        nobs = batch["next_obs"]
        acts = batch["actions"].astype(jnp.int32)
        rews = batch["rewards"]
        not_done = 1.0 - batch["dones"].astype(jnp.float32)
        mask = batch["mask"]
        # gradient (and TD) only after the burn-in prefix
        mask = mask.at[:, :burn].set(0.0)
        denom = jnp.maximum(mask.sum(), 1.0)
        b = obs.shape[0]
        zero = zero_carry(b, cfg["lstm_size"])

        def q_unroll(p, seq):
            _, q = self.qnet.apply({"params": p}, zero, seq,
                                   method=_RecurrentQNet.unroll)
            return q  # (B, T, A)

        tq_next = q_unroll(target_params, nobs)
        if cfg.get("double_q", True):
            best = jnp.argmax(q_unroll(params, nobs), axis=-1)
        else:
            best = jnp.argmax(tq_next, axis=-1)
        q_next = jnp.take_along_axis(tq_next, best[..., None],
                                     axis=-1)[..., 0]
        y = jax.lax.stop_gradient(rews + gamma * not_done * q_next)

        def loss_fn(p):
            q = q_unroll(p, obs)
            q_sel = jnp.take_along_axis(q, acts[..., None],
                                        axis=-1)[..., 0]
            td = (q_sel - y) * mask
            loss = jnp.sum(td ** 2) / denom
            return loss, {"mean_q": jnp.sum(q_sel * mask) / denom,
                          "mean_td_error":
                              jnp.sum(jnp.abs(td)) / denom}

        (loss_val, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        stats = dict(stats)
        stats["loss"] = loss_val
        return params, opt_state, stats

    # ---- acting ----

    def _act(self, obs, epsilon: float) -> int:
        self._carry, q = self._jit_step(
            self.params, self._carry,
            jnp.asarray(obs, jnp.float32)[None])
        if self._np_rng.random() < epsilon:
            return int(self._np_rng.integers(self.n_actions))
        return int(np.argmax(np.asarray(q)[0]))

    def _collect(self, num_steps: int, epsilon: float) -> int:
        for _ in range(num_steps):
            a = self._act(self._obs, epsilon)
            nobs, r, term, trunc, _ = self.env.step(a)
            rows = self._ep_rows
            rows["obs"].append(np.asarray(self._obs, np.float32))
            rows["actions"].append(np.int64(a))
            rows["rewards"].append(np.float32(r))
            rows["dones"].append(bool(term))
            rows["next_obs"].append(np.asarray(nobs, np.float32))
            self._ep_reward += float(r)
            if term or trunc:
                self.replay.add_episode(
                    {k: np.stack(v) if np.asarray(v[0]).ndim
                     else np.asarray(v) for k, v in rows.items()})
                self._episode_reward_window.append(self._ep_reward)
                self._reset_episode()
            else:
                self._obs = nobs
        return num_steps

    # ---- Trainable / Algorithm surface ----

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        n = self._collect(cfg["rollout_fragment_length"], eps)
        self._timesteps_total += n
        stats: Dict[str, float] = {}
        if self.replay.num_steps >= cfg["learning_starts"]:
            for _ in range(max(1, cfg.get("training_intensity", 1))):
                train = self.replay.sample(cfg["train_batch_size"])
                jbatch = {k: jnp.asarray(v) for k, v in train.items()}
                self.params, self.opt_state, jstats = self._jit_update(
                    self.params, self.target_params, self.opt_state,
                    jbatch)
                stats = {k: float(v) for k, v in jstats.items()}
            self._maybe_sync_target(n)
        return {
            "num_env_steps_sampled_this_iter": n,
            "epsilon": eps,
            "replay_episodes": len(self.replay._episodes),
            "replay_steps": self.replay.num_steps,
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        carry_box = [zero_carry(1, self.config["lstm_size"])]

        def reset_carry():
            carry_box[0] = zero_carry(1, self.config["lstm_size"])

        def act(obs):
            carry_box[0], q = self._jit_step(
                self.params, carry_box[0],
                jnp.asarray(obs, jnp.float32)[None])
            return int(np.argmax(np.asarray(q)[0]))

        out = self._eval_episodes(act, num_episodes,
                                  on_reset=reset_carry)
        self._reset_episode()
        return out

