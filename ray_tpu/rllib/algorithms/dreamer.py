"""Dreamer (V1) — learned world model + actor-critic in imagination.

Reference analogue: rllib/algorithms/dreamer/ (dreamer.py,
dreamer_torch_policy.py, dreamer_model.py; Hafner et al. 2020): an RSSM
world model (deterministic GRU path + stochastic latent) trained on
replayed sequences by reconstruction + reward prediction + KL, and an
actor/value pair trained ENTIRELY on imagined latent rollouts with
lambda-returns, the actor by backprop THROUGH the learned dynamics
(reparameterized latents — no likelihood-ratio estimator). TPU-first
shape: all three updates are single jitted programs over [B, T, ...]
sequence batches; imagination is a lax.scan over the horizon.

Vector-observation variant (MLP encoder/decoder) — the reference's
conv stack only changes the encoder/decoder modules.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm
from ray_tpu.rllib.env import make_env


class _RSSM(nn.Module):
    """h_t = GRU(h_{t-1}, [z_{t-1}, a_{t-1}]); prior p(z|h); posterior
    q(z|h, embed(obs))."""
    deter: int = 64
    stoch: int = 8
    hidden: int = 64

    def setup(self):
        self.gru = nn.GRUCell(features=self.deter)
        self.inp = nn.Dense(self.hidden)
        self.prior_net = nn.Dense(2 * self.stoch)
        self.post_net = nn.Dense(2 * self.stoch)

    def _stats(self, net, x):
        mean, std = jnp.split(net(x), 2, axis=-1)
        return mean, nn.softplus(std) + 0.1

    def step_prior(self, h, z, a):
        x = nn.relu(self.inp(jnp.concatenate([z, a], -1)))
        h, _ = self.gru(h, x)
        mean, std = self._stats(self.prior_net, h)
        return h, mean, std

    def posterior(self, h, embed):
        return self._stats(self.post_net,
                           jnp.concatenate([h, embed], -1))


class _WorldModel(nn.Module):
    obs_dim: int
    act_dim: int
    deter: int = 64
    stoch: int = 8
    hidden: int = 64

    def setup(self):
        self.rssm = _RSSM(self.deter, self.stoch, self.hidden)
        self.encoder = nn.Sequential([nn.Dense(self.hidden), nn.relu,
                                      nn.Dense(self.hidden)])
        self.decoder = nn.Sequential([nn.Dense(self.hidden), nn.relu,
                                      nn.Dense(self.obs_dim)])
        self.reward_head = nn.Sequential([nn.Dense(self.hidden), nn.relu,
                                          nn.Dense(1)])

    def observe(self, obs_seq, act_seq, rng):
        """obs_seq [B,T,do], act_seq [B,T,da] (act at t-1; zeros at 0).
        Returns posterior features [B,T,deter+stoch] + KL terms.
        The T loop is a Python unroll (tiny seq_len; XLA fuses the GRU
        chain) — keeps submodule calls linen-legal without nn.scan."""
        b, t, _ = obs_seq.shape
        embed = self.encoder(obs_seq)
        h = jnp.zeros((b, self.deter))
        z = jnp.zeros((b, self.stoch))
        feats, kls = [], []
        key = rng
        for i in range(t):
            h, p_mean, p_std = self.rssm.step_prior(h, z, act_seq[:, i])
            q_mean, q_std = self.rssm.posterior(h, embed[:, i])
            key, sub = jax.random.split(key)
            z = q_mean + q_std * jax.random.normal(sub, q_mean.shape)
            kls.append(self._kl(q_mean, q_std, p_mean, p_std))
            feats.append(jnp.concatenate([h, z], -1))
        return jnp.stack(feats, 1), jnp.stack(kls, 1)

    @staticmethod
    def _kl(qm, qs, pm, ps):
        return jnp.sum(
            jnp.log(ps / qs) + (qs ** 2 + (qm - pm) ** 2)
            / (2 * ps ** 2) - 0.5, axis=-1)

    def decode(self, feat):
        return self.decoder(feat)

    def reward(self, feat):
        return self.reward_head(feat)[..., 0]

    def imagine_step(self, h, z, a, key):
        h, mean, std = self.rssm.step_prior(h, z, a)
        z = mean + std * jax.random.normal(key, mean.shape)
        return h, z

    def init_all(self, obs_seq, act_seq, rng):
        """Touches every head so ``init`` creates the full param tree."""
        feat, _ = self.observe(obs_seq, act_seq, rng)
        return self.decode(feat), self.reward(feat)


class _Actor(nn.Module):
    act_dim: int
    hidden: int = 64

    @nn.compact
    def __call__(self, feat):
        x = nn.relu(nn.Dense(self.hidden)(feat))
        mean = nn.Dense(self.act_dim)(x)
        logstd = self.param("logstd", nn.initializers.constant(-1.0),
                            (self.act_dim,))
        return jnp.tanh(mean), jnp.exp(logstd)


class _Value(nn.Module):
    hidden: int = 64

    @nn.compact
    def __call__(self, feat):
        x = nn.relu(nn.Dense(self.hidden)(feat))
        return nn.Dense(1)(x)[..., 0]


class DreamerConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or Dreamer)
        self._config.update({
            "env": "Pendulum-v1",
            "deter_size": 64,
            "stoch_size": 8,
            "hidden": 64,
            "model_lr": 3e-3,
            "actor_lr": 1e-3,
            "value_lr": 3e-3,
            "gamma": 0.97,
            "lambda_": 0.95,
            "imagine_horizon": 10,
            "kl_coeff": 0.3,
            "free_nats": 1.0,
            "batch_size": 24,     # sequences per model update
            "seq_len": 16,
            "prefill_steps": 1_000,
            "rollout_fragment_length": 200,
            "train_steps_per_iteration": 20,
            "expl_noise": 0.3,
        })


class Dreamer(LocalAlgorithm):
    """DreamerV1 (reference: dreamer.py training loop — collect,
    then model/actor/value updates on replayed sequences)."""

    _default_config_cls = DreamerConfig

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        self.env = make_env(cfg["env"], cfg.get("env_config"))
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.act_dim = int(np.prod(self.env.action_space.shape))
        self.act_low = np.asarray(self.env.action_space.low, np.float32)
        self.act_high = np.asarray(self.env.action_space.high, np.float32)

        self.wm = _WorldModel(self.obs_dim, self.act_dim,
                              cfg["deter_size"], cfg["stoch_size"],
                              cfg["hidden"])
        self.actor = _Actor(self.act_dim, cfg["hidden"])
        self.value = _Value(cfg["hidden"])

        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        k1, k2, k3, k4 = jax.random.split(self._rng, 4)
        dummy_obs = jnp.zeros((1, 2, self.obs_dim))
        dummy_act = jnp.zeros((1, 2, self.act_dim))
        self.wm_params = self.wm.init(
            {"params": k1}, dummy_obs, dummy_act, k2,
            method=_WorldModel.init_all)["params"]
        feat_dim = cfg["deter_size"] + cfg["stoch_size"]
        self.actor_params = self.actor.init(
            k3, jnp.zeros((1, feat_dim)))["params"]
        self.value_params = self.value.init(
            k4, jnp.zeros((1, feat_dim)))["params"]

        self.wm_opt = optax.adam(cfg["model_lr"])
        self.actor_opt = optax.adam(cfg["actor_lr"])
        self.value_opt = optax.adam(cfg["value_lr"])
        self.wm_opt_state = self.wm_opt.init(self.wm_params)
        self.actor_opt_state = self.actor_opt.init(self.actor_params)
        self.value_opt_state = self.value_opt.init(self.value_params)

        # LocalAlgorithm checkpoint plumbing
        self.params = {"wm": self.wm_params, "actor": self.actor_params,
                       "value": self.value_params}
        self.target_params = self.params
        self.opt_state = (self.wm_opt_state, self.actor_opt_state,
                          self.value_opt_state)

        self._jit_update = jax.jit(self._update_impl)
        self._jit_filter = jax.jit(self._filter_impl)

        # episode replay: list of dicts of np arrays
        self._episodes: List[Dict[str, np.ndarray]] = []
        self._init_local_state()
        self._reset_collector()

    # ---- acting (posterior filtering) ----

    def _reset_collector(self):
        self._obs, _ = self.env.reset(seed=self.config.get("seed"))
        self._h = jnp.zeros((1, self.config["deter_size"]))
        self._z = jnp.zeros((1, self.config["stoch_size"]))
        self._prev_a = jnp.zeros((1, self.act_dim))
        self._ep = {"obs": [], "actions": [], "rewards": []}
        self._episode_reward = 0.0

    def _filter_impl(self, wm_params, actor_params, h, z, prev_a, obs,
                     key):
        """One posterior-filter step + action."""
        embed = self.wm.apply({"params": wm_params}, obs,
                              method=lambda m, o: m.encoder(o))
        h, _pm, _ps = self.wm.apply(
            {"params": wm_params}, h, z, prev_a,
            method=lambda m, h_, z_, a_: m.rssm.step_prior(h_, z_, a_))
        q_mean, q_std = self.wm.apply(
            {"params": wm_params}, h, embed,
            method=lambda m, h_, e_: m.rssm.posterior(h_, e_))
        k1, k2 = jax.random.split(key)
        z = q_mean + q_std * jax.random.normal(k1, q_mean.shape)
        feat = jnp.concatenate([h, z], -1)
        mean, std = self.actor.apply({"params": actor_params}, feat)
        a = mean + std * jax.random.normal(k2, mean.shape)
        return h, z, a

    def _next_key(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _env_action(self, a: np.ndarray, noise: float) -> np.ndarray:
        a = a + noise * self._np_rng.standard_normal(a.shape)
        half = (self.act_high - self.act_low) / 2.0
        mid = (self.act_high + self.act_low) / 2.0
        return np.clip(mid + half * a, self.act_low, self.act_high)

    def _collect(self, num_steps: int, noise: float) -> int:
        for _ in range(num_steps):
            self._h, self._z, a = self._jit_filter(
                self.wm_params, self.actor_params, self._h, self._z,
                self._prev_a, jnp.asarray(self._obs[None], jnp.float32),
                self._next_key())
            a_np = np.asarray(a)[0]
            env_a = self._env_action(a_np, noise)
            nobs, r, term, trunc, _ = self.env.step(env_a)
            self._ep["obs"].append(np.asarray(self._obs, np.float32))
            self._ep["actions"].append(np.asarray(a_np, np.float32))
            self._ep["rewards"].append(np.float32(r))
            self._prev_a = a
            self._episode_reward += float(r)
            if term or trunc:
                self._episodes.append(
                    {k: np.stack(v) for k, v in self._ep.items()})
                self._episodes = self._episodes[-200:]
                self._episode_reward_window.append(self._episode_reward)
                self._reset_collector()
            else:
                self._obs = nobs
        return num_steps

    # ---- jitted three-headed update ----

    def _sample_sequences(self) -> Optional[Dict[str, jnp.ndarray]]:
        cfg = self.config
        T = cfg["seq_len"]
        eligible = [e for e in self._episodes
                    if e["obs"].shape[0] >= T]
        if not eligible:
            return None
        obs_b, act_b, rew_b = [], [], []
        for _ in range(cfg["batch_size"]):
            ep = eligible[self._np_rng.integers(len(eligible))]
            start = self._np_rng.integers(0, ep["obs"].shape[0] - T + 1)
            obs_b.append(ep["obs"][start:start + T])
            # action at index t is a_{t-1} (zeros at episode start)
            acts = ep["actions"][start:start + T]
            prev = np.concatenate(
                [np.zeros((1, self.act_dim), np.float32)
                 if start == 0 else
                 ep["actions"][start - 1:start], acts[:-1]])
            act_b.append(prev)
            rew_b.append(ep["rewards"][start:start + T])
        return {"obs": jnp.asarray(np.stack(obs_b)),
                "prev_actions": jnp.asarray(np.stack(act_b)),
                "rewards": jnp.asarray(np.stack(rew_b))}

    def _update_impl(self, wm_params, actor_params, value_params,
                     wm_os, actor_os, value_os, batch, key):
        cfg = self.config
        k_model, k_imagine = jax.random.split(key)

        # --- world model ---
        def wm_loss_fn(p):
            feat, kls = self.wm.apply(
                {"params": p}, batch["obs"], batch["prev_actions"],
                k_model, method=_WorldModel.observe)
            recon = self.wm.apply({"params": p}, feat,
                                  method=_WorldModel.decode)
            rhat = self.wm.apply({"params": p}, feat,
                                 method=_WorldModel.reward)
            recon_l = jnp.mean(jnp.sum(
                (recon - batch["obs"]) ** 2, -1))
            reward_l = jnp.mean((rhat - batch["rewards"]) ** 2)
            kl = jnp.mean(jnp.maximum(kls, cfg["free_nats"]))
            return (recon_l + reward_l + cfg["kl_coeff"] * kl,
                    (feat, recon_l, reward_l, kl))

        (wm_l, (feat, recon_l, reward_l, kl)), wm_grads = \
            jax.value_and_grad(wm_loss_fn, has_aux=True)(wm_params)
        upd, wm_os = self.wm_opt.update(wm_grads, wm_os, wm_params)
        wm_params = optax.apply_updates(wm_params, upd)

        # --- imagination from (stop-gradient) posterior states ---
        feat = jax.lax.stop_gradient(feat.reshape(-1, feat.shape[-1]))
        h0 = feat[:, :cfg["deter_size"]]
        z0 = feat[:, cfg["deter_size"]:]

        def imagine(actor_p, h, z, key):
            def step(carry, k):
                h, z = carry
                f = jnp.concatenate([h, z], -1)
                mean, std = self.actor.apply({"params": actor_p}, f)
                k1, k2 = jax.random.split(k)
                a = mean + std * jax.random.normal(k1, mean.shape)
                h, z = self.wm.apply(
                    {"params": wm_params}, h, z, a, k2,
                    method=lambda m, h_, z_, a_, kk: m.imagine_step(
                        h_, z_, a_, kk))
                return (h, z), jnp.concatenate([h, z], -1)

            keys = jax.random.split(key, cfg["imagine_horizon"])
            (_, _), feats = jax.lax.scan(step, (h, z), keys)
            return feats  # [H, B, feat]

        def actor_loss_fn(actor_p):
            feats = imagine(actor_p, h0, z0, k_imagine)
            rewards = self.wm.apply({"params": wm_params}, feats,
                                    method=_WorldModel.reward)
            values = self.value.apply({"params": value_params}, feats)
            # lambda-returns computed backwards (Hafner eq. 6)
            gamma, lam = cfg["gamma"], cfg["lambda_"]

            def lam_step(nxt, rv):
                r, v_next = rv
                ret = r + gamma * ((1 - lam) * v_next + lam * nxt)
                return ret, ret

            last = values[-1]
            _, rets = jax.lax.scan(
                lam_step, last,
                (rewards[:-1][::-1], values[1:][::-1]))
            returns = rets[::-1]  # [H-1, B]
            return -jnp.mean(returns), (feats, returns)

        (actor_l, (feats, returns)), actor_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True)(actor_params)
        upd, actor_os = self.actor_opt.update(actor_grads, actor_os,
                                              actor_params)
        actor_params = optax.apply_updates(actor_params, upd)

        # --- value regression on the imagined lambda-returns ---
        feats_sg = jax.lax.stop_gradient(feats[:-1])
        returns_sg = jax.lax.stop_gradient(returns)

        def value_loss_fn(vp):
            v = self.value.apply({"params": vp}, feats_sg)
            return jnp.mean((v - returns_sg) ** 2)

        value_l, value_grads = jax.value_and_grad(value_loss_fn)(
            value_params)
        upd, value_os = self.value_opt.update(value_grads, value_os,
                                              value_params)
        value_params = optax.apply_updates(value_params, upd)

        stats = {"model_loss": wm_l, "recon_loss": recon_l,
                 "reward_loss": reward_l, "kl": kl,
                 "actor_loss": actor_l, "value_loss": value_l}
        return (wm_params, actor_params, value_params,
                wm_os, actor_os, value_os, stats)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        if self._timesteps_total < cfg["prefill_steps"]:
            n = self._collect(cfg["prefill_steps"], noise=1.0)
        else:
            n = self._collect(cfg["rollout_fragment_length"],
                              noise=cfg["expl_noise"])
        self._timesteps_total += n
        stats: Dict[str, float] = {}
        for _ in range(cfg["train_steps_per_iteration"]):
            batch = self._sample_sequences()
            if batch is None:
                break
            (self.wm_params, self.actor_params, self.value_params,
             self.wm_opt_state, self.actor_opt_state,
             self.value_opt_state, jstats) = self._jit_update(
                self.wm_params, self.actor_params, self.value_params,
                self.wm_opt_state, self.actor_opt_state,
                self.value_opt_state, batch, self._next_key())
            stats = {k: float(v) for k, v in jstats.items()}
        self.params = {"wm": self.wm_params, "actor": self.actor_params,
                       "value": self.value_params}
        self.opt_state = (self.wm_opt_state, self.actor_opt_state,
                          self.value_opt_state)
        return {
            "num_env_steps_sampled_this_iter": n,
            "num_episodes": len(self._episodes),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        rewards = []
        for ep in range(num_episodes):
            obs, _ = self.env.reset(seed=20_000 + ep)
            h = jnp.zeros((1, self.config["deter_size"]))
            z = jnp.zeros((1, self.config["stoch_size"]))
            prev_a = jnp.zeros((1, self.act_dim))
            total, done = 0.0, False
            while not done:
                h, z, a = self._jit_filter(
                    self.wm_params, self.actor_params, h, z, prev_a,
                    jnp.asarray(obs[None], jnp.float32),
                    self._next_key())
                env_a = self._env_action(np.asarray(a)[0], 0.0)
                obs, r, term, trunc, _ = self.env.step(env_a)
                prev_a = a
                total += float(r)
                done = term or trunc
            rewards.append(total)
        # collector state untouched: eval used its own h/z stream
        return {"evaluation": {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_reward_min": float(np.min(rewards)),
            "episode_reward_max": float(np.max(rewards)),
        }}

    def save_checkpoint(self) -> Dict[str, Any]:
        return {"params": jax.device_get(self.params),
                "target_params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state),
                "iteration": self._iteration,
                "timesteps_total": self._timesteps_total}

    def load_checkpoint(self, state: Dict[str, Any]):
        super().load_checkpoint(state)
        self.wm_params = self.params["wm"]
        self.actor_params = self.params["actor"]
        self.value_params = self.params["value"]
        (self.wm_opt_state, self.actor_opt_state,
         self.value_opt_state) = self.opt_state
