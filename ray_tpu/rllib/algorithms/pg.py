"""PG (vanilla policy gradient / REINFORCE) and A2C.

Reference analogue: rllib/algorithms/pg/ and rllib/algorithms/a2c/.
Both reuse the PPO rollout machinery (GAE postprocessing) with simpler
jitted losses: PG uses full-return advantages, A2C the one-network
actor-critic loss without PPO clipping.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.rollout_worker import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import SampleBatch


class PGPolicy(JaxPolicy):
    def postprocess_trajectory(self, batch):
        from ray_tpu.rllib.postprocessing import \
            compute_gae_for_sample_batch
        # lambda=1 GAE == discounted-return advantages (REINFORCE w/
        # value baseline if vf present)
        return compute_gae_for_sample_batch(
            self, batch, self.config.get("gamma", 0.99), 1.0)

    def loss(self, params, batch):
        dist_inputs, _ = self.model.apply(
            {"params": params}, batch[SampleBatch.OBS])
        logp = self.dist_logp(dist_inputs, batch[SampleBatch.ACTIONS])
        adv = batch[SampleBatch.ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg_loss = -jnp.mean(logp * adv)
        return pg_loss, {"policy_loss": pg_loss,
                         "entropy": jnp.mean(
                             self.dist_entropy(dist_inputs))}


class PGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PG)
        self._config.update({"lr": 4e-3, "train_batch_size": 500})


class PG(Algorithm):
    _policy_cls = PGPolicy
    _default_config_cls = PGConfig

    def training_step(self) -> Dict[str, Any]:
        batch = synchronous_parallel_sample(
            self.workers, max_env_steps=self.config["train_batch_size"])
        self._timesteps_total += batch.count
        stats = self.workers.local_worker.policy.learn_on_batch(batch)
        self.workers.sync_weights()
        return {"num_env_steps_sampled_this_iter": batch.count,
                **{f"learner/{k}": v for k, v in stats.items()}}


class A2CPolicy(JaxPolicy):
    def postprocess_trajectory(self, batch):
        from ray_tpu.rllib.postprocessing import \
            compute_gae_for_sample_batch
        return compute_gae_for_sample_batch(
            self, batch, self.config.get("gamma", 0.99),
            self.config.get("lambda", 1.0))

    def loss(self, params, batch):
        cfg = self.config
        dist_inputs, vf = self.model.apply(
            {"params": params}, batch[SampleBatch.OBS])
        logp = self.dist_logp(dist_inputs, batch[SampleBatch.ACTIONS])
        adv = batch[SampleBatch.ADVANTAGES]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        pg_loss = -jnp.mean(logp * adv)
        vf_loss = jnp.mean((vf - batch[SampleBatch.VALUE_TARGETS]) ** 2)
        entropy = jnp.mean(self.dist_entropy(dist_inputs))
        total = (pg_loss + cfg.get("vf_loss_coeff", 0.5) * vf_loss
                 - cfg.get("entropy_coeff", 0.01) * entropy)
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}


class A2CConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A2C)
        self._config.update({
            "lr": 1e-3, "train_batch_size": 500,
            "vf_loss_coeff": 0.5, "entropy_coeff": 0.01,
            "grad_clip": 40.0, "lambda": 1.0,
        })


class A2C(PG):
    _policy_cls = A2CPolicy
    _default_config_cls = A2CConfig
