"""ES — OpenAI evolution strategies (Salimans et al. 2017).

Reference analogue: rllib/algorithms/es/ (es.py, optimizers.py,
utils.py): a big shared noise table broadcast ONCE through the object
store (zero-copy numpy from plasma on every worker — reference
es.py create_shared_noise), antithetic perturbation rollouts on remote
workers, centered-rank-weighted gradient estimate, Adam on the flat
parameter vector. Evaluation/checkpointing ride the normal Algorithm
path: the flat theta maps back onto the local JaxPolicy's pytree.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.flatten_util
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import Discrete, make_env
from ray_tpu.rllib.models import make_model
from ray_tpu.rllib.policy import JaxPolicy


def create_shared_noise(size: int, seed: int = 123) -> np.ndarray:
    """One float32 noise pool shared by every worker (reference:
    es.py:43 create_shared_noise — 250M floats; default here is smaller
    and configurable via ``noise_table_size``)."""
    return np.random.default_rng(seed).standard_normal(
        size, dtype=np.float32)


def compute_centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping: values → ranks in [-0.5, 0.5] (reference:
    es/utils.py compute_centered_ranks)."""
    ranks = np.empty(x.size, dtype=np.float32)
    ranks[x.ravel().argsort()] = np.arange(x.size, dtype=np.float32)
    return (ranks / max(1, x.size - 1) - 0.5).reshape(x.shape)


class _PerturbationWorker:
    """Holds env + shared noise; evaluates theta ± sigma·eps pairs."""

    def __init__(self, config: Dict[str, Any], noise: np.ndarray,
                 seed: int):
        self.config = config
        self.noise = noise
        self.env = make_env(config["env"], config.get("env_config"))
        self.model = make_model(self.env.observation_space,
                                self.env.action_space,
                                config.get("model"))
        self.discrete = isinstance(self.env.action_space, Discrete)
        dummy = jnp.zeros(
            (1, *self.env.observation_space.shape), jnp.float32)
        params = self.model.init(jax.random.PRNGKey(seed), dummy)["params"]
        flat, self._unravel = jax.flatten_util.ravel_pytree(params)
        self.dim = flat.size
        self._rng = np.random.default_rng(seed)
        self._fwd = jax.jit(self._fwd_impl)

    def _fwd_impl(self, theta, obs):
        dist_inputs, _ = self.model.apply(
            {"params": self._unravel(theta)}, obs[None])
        if self.discrete:
            return jnp.argmax(dist_inputs[0])
        mean, _ = jnp.split(dist_inputs[0], 2, axis=-1)
        return mean

    def _act(self, theta, obs):
        a = np.asarray(self._fwd(theta, jnp.asarray(obs)))
        if self.discrete:
            return int(a)
        sp = self.env.action_space
        return np.clip(a, sp.low, sp.high).astype(np.float32)

    def rollout(self, theta: np.ndarray,
                limit: int) -> Tuple[float, int]:
        obs, _ = self.env.reset(seed=int(self._rng.integers(2 ** 31)))
        total, steps = 0.0, 0
        while steps < limit:
            obs, r, term, trunc, _ = self.env.step(self._act(theta, obs))
            total += float(r)
            steps += 1
            if term or trunc:
                break
        return total, steps

    def do_rollouts(self, theta: np.ndarray, num_pairs: int,
                    sigma: float, limit: int) -> List[Tuple]:
        """Antithetic pairs: [(noise_idx, r_plus, r_minus, steps)]."""
        theta = np.asarray(theta, np.float32)
        out = []
        for _ in range(num_pairs):
            idx = int(self._rng.integers(0, self.noise.size - self.dim))
            eps = self.noise[idx:idx + self.dim]
            rp, sp = self.rollout(theta + sigma * eps, limit)
            rn, sn = self.rollout(theta - sigma * eps, limit)
            out.append((idx, rp, rn, sp + sn))
        return out


PerturbationWorker = ray_tpu.remote(_PerturbationWorker)


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ES)
        self._config.update({
            "num_workers": 2,
            "sigma": 0.05,          # perturbation std (es.py noise_stdev)
            "stepsize": 0.02,       # Adam lr on theta
            "rollouts_per_worker": 10,  # antithetic PAIRS per worker/iter
            "l2_coeff": 0.005,
            "episode_horizon": 500,
            "noise_table_size": 4_000_000,
            "noise_seed": 123,
        })


class ES(Algorithm):
    _policy_cls = JaxPolicy  # inference/checkpoint only; never .loss()
    _default_config_cls = ESConfig

    def setup(self, config):
        base = dict(config or {})
        self._es_num_workers = base.get(
            "num_workers", self._default_config_cls()["num_workers"])
        base["num_workers"] = 0  # no gradient rollout actors
        super().setup(base)
        cfg = self.config
        policy = self.workers.local_worker.policy
        flat, self._unravel = jax.flatten_util.ravel_pytree(policy.params)
        self.theta = np.asarray(flat, np.float32)
        self.dim = self.theta.size
        if cfg["noise_table_size"] <= self.dim:
            raise ValueError(
                f"noise_table_size ({cfg['noise_table_size']}) must "
                f"exceed the flat parameter count ({self.dim}); raise "
                "it or shrink the model")
        self.noise = create_shared_noise(cfg["noise_table_size"],
                                         cfg.get("noise_seed", 123))
        noise_ref = ray_tpu.put(self.noise)
        seed = cfg.get("seed") or 0
        self._es_workers = [
            PerturbationWorker.remote(
                {k: cfg.get(k) for k in
                 ("env", "env_config", "model")},
                noise_ref, seed * 1000 + i + 1)
            for i in range(max(1, self._es_num_workers))]
        self.optimizer = optax.adam(cfg["stepsize"])
        self.opt_state = self.optimizer.init(self.theta)

    def _gradient(self, idxs, r_pos, r_neg) -> np.ndarray:
        # centered ranks over the FULL (pos|neg) return matrix
        ranks = compute_centered_ranks(
            np.stack([r_pos, r_neg], axis=1))
        w = ranks[:, 0] - ranks[:, 1]
        g = np.zeros(self.dim, np.float32)
        for wi, idx in zip(w, idxs):
            g += wi * self.noise[idx:idx + self.dim]
        return g / (len(idxs) * self.config["sigma"])

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        theta_ref = ray_tpu.put(self.theta)
        results = ray_tpu.get([
            w.do_rollouts.remote(theta_ref, cfg["rollouts_per_worker"],
                                 cfg["sigma"], cfg["episode_horizon"])
            for w in self._es_workers])
        flat = [t for worker_out in results for t in worker_out]
        idxs = [t[0] for t in flat]
        r_pos = np.array([t[1] for t in flat], np.float32)
        r_neg = np.array([t[2] for t in flat], np.float32)
        steps = int(sum(t[3] for t in flat))
        self._timesteps_total += steps

        g = self._gradient(idxs, r_pos, r_neg)
        g -= cfg["l2_coeff"] * self.theta  # weight decay toward 0
        # optax minimizes: feed the negative of the ascent direction
        updates, self.opt_state = self.optimizer.update(
            -g, self.opt_state, self.theta)
        self.theta = np.asarray(
            optax.apply_updates(self.theta, updates), np.float32)

        # reflect theta onto the eval/checkpoint policy
        policy = self.workers.local_worker.policy
        policy.params = self._unravel(jnp.asarray(self.theta))
        all_r = np.concatenate([r_pos, r_neg])
        self._episode_reward_window.extend(all_r.tolist())
        return {
            "num_env_steps_sampled_this_iter": steps,
            "episodes_this_iter": all_r.size,
            "perturbation_reward_mean": float(all_r.mean()),
            "update_gnorm": float(np.linalg.norm(g)),
        }

    def save_checkpoint(self) -> Dict[str, Any]:
        state = super().save_checkpoint()
        state["theta"] = self.theta.copy()
        state["es_opt_state"] = jax.device_get(self.opt_state)
        return state

    def load_checkpoint(self, state: Dict[str, Any]):
        super().load_checkpoint(state)
        if "theta" in state:
            self.theta = np.asarray(state["theta"], np.float32)
            self.opt_state = jax.tree_util.tree_map(
                jnp.asarray, state["es_opt_state"],
                is_leaf=lambda x: isinstance(x, (np.ndarray, np.generic)))
        else:
            # pre-theta checkpoint: re-flatten the restored policy
            flat, _ = jax.flatten_util.ravel_pytree(
                self.workers.local_worker.policy.params)
            self.theta = np.asarray(flat, np.float32)

    def cleanup(self):
        for w in self._es_workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        super().cleanup()


class ARSConfig(ESConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ARS)
        self._config.update({
            "sigma": 0.05,
            "stepsize": 0.02,
            "rollouts_per_worker": 8,
            # top directions kept for the update (ARS-V1t; Mania et al.)
            "num_top_directions": 8,
        })


class ARS(ES):
    """Augmented random search (reference: rllib/algorithms/ars/ars.py):
    same worker machinery as ES; the update keeps only the top-k
    directions by max(r+, r-) and scales by the reward std of that
    elite set instead of fitness shaping."""

    _default_config_cls = ARSConfig

    def _gradient(self, idxs, r_pos, r_neg) -> np.ndarray:
        k = min(self.config.get("num_top_directions", 8), len(idxs))
        order = np.argsort(-np.maximum(r_pos, r_neg))[:k]
        elite = np.concatenate([r_pos[order], r_neg[order]])
        sigma_r = float(elite.std()) + 1e-8
        g = np.zeros(self.dim, np.float32)
        for i in order:
            g += (r_pos[i] - r_neg[i]) * self.noise[
                idxs[i]:idxs[i] + self.dim]
        return g / (k * sigma_r)
