"""DDPG + TD3 — deterministic-policy-gradient continuous control.

Reference analogue: rllib/algorithms/ddpg/ (ddpg.py, ddpg_torch_policy.py)
and rllib/algorithms/td3.py — in the reference TD3 is a DDPG preset
(twin_q + delayed policy updates + target policy smoothing); same here.
TPU-first shape: critic and actor updates are two jitted programs over
replayed batches; the actor program runs every ``policy_delay`` critic
steps; polyak target blending rides inside the critic program.
"""

from __future__ import annotations

from typing import Any, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import _stats_to_host
from ray_tpu.rllib.env import Box
from ray_tpu.rllib.replay_buffers import ReplayBuffer
from ray_tpu.rllib.rollout_worker import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import SampleBatch


class _DDPGNets(nn.Module):
    act_dim: int
    twin_q: bool
    hidden: int = 256

    def setup(self):
        self.pi_net = nn.Sequential([
            nn.Dense(self.hidden), nn.relu,
            nn.Dense(self.hidden), nn.relu,
            nn.Dense(self.act_dim), nn.tanh])
        self.q1_net = nn.Sequential([
            nn.Dense(self.hidden), nn.relu,
            nn.Dense(self.hidden), nn.relu, nn.Dense(1)])
        if self.twin_q:
            self.q2_net = nn.Sequential([
                nn.Dense(self.hidden), nn.relu,
                nn.Dense(self.hidden), nn.relu, nn.Dense(1)])

    def pi(self, obs):
        return self.pi_net(obs)

    def q(self, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        q1 = self.q1_net(x)[..., 0]
        q2 = self.q2_net(x)[..., 0] if self.twin_q else q1
        return q1, q2

    def __call__(self, obs, act):
        return self.pi(obs), self.q(obs, act)


class DDPGPolicy:
    """Worker-facing API parity with JaxPolicy (compute_actions /
    postprocess_trajectory / learn_on_batch / get,set_weights)."""

    def __init__(self, obs_space, action_space, config: Dict[str, Any]):
        assert isinstance(action_space, Box), "DDPG is continuous-only"
        self.observation_space = obs_space
        self.action_space = action_space
        self.config = config
        self.act_dim = int(np.prod(action_space.shape))
        self.low = np.asarray(action_space.low, np.float32)
        self.high = np.asarray(action_space.high, np.float32)
        self.model = _DDPGNets(self.act_dim,
                               bool(config.get("twin_q", False)))
        self._rng = jax.random.PRNGKey(config.get("seed") or 0)
        self._np_rng = np.random.default_rng(config.get("seed"))
        obs_dim = obs_space.shape or (1,)
        dummy_o = jnp.zeros((1, *obs_dim), jnp.float32)
        dummy_a = jnp.zeros((1, self.act_dim), jnp.float32)
        self.params = self.model.init(self._next_rng(), dummy_o,
                                      dummy_a)["params"]
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        self.pi_optimizer = optax.adam(config.get("actor_lr", 1e-3))
        self.q_optimizer = optax.adam(config.get("critic_lr", 1e-3))
        self.pi_opt_state = self.pi_optimizer.init(self.params)
        self.q_opt_state = self.q_optimizer.init(self.params)
        self._jit_act = jax.jit(self._act_impl)
        self._jit_critic = jax.jit(self._critic_update)
        self._jit_actor = jax.jit(self._actor_update)
        self.global_timestep = 0
        self._learn_steps = 0
        # host-side exploration noise scale, annealable via set_exploration
        self.exploration_noise = config.get("exploration_noise", 0.1)

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ---- inference ----

    def _act_impl(self, params, obs):
        return self.model.apply({"params": params}, obs,
                                method=_DDPGNets.pi)

    def compute_actions(self, obs, explore=True):
        act = np.asarray(self._jit_act(self.params, jnp.asarray(obs)))
        if explore:
            act = act + self._np_rng.normal(
                0.0, self.exploration_noise, act.shape).astype(np.float32)
            act = np.clip(act, -1.0, 1.0)
        scaled = self.low + (act + 1.0) * 0.5 * (self.high - self.low)
        n = len(scaled)
        return scaled.astype(np.float32), {
            SampleBatch.ACTION_LOGP: np.zeros(n, np.float32),
            SampleBatch.VF_PREDS: np.zeros(n, np.float32),
            "raw_actions": act.astype(np.float32),
        }

    def postprocess_trajectory(self, batch):
        return batch

    # ---- learning ----

    def _critic_update(self, params, target_params, q_opt_state, batch,
                       rng):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        obs = batch[SampleBatch.OBS]
        nobs = batch[SampleBatch.NEXT_OBS]
        acts = batch["raw_actions"]
        rews = batch[SampleBatch.REWARDS]
        not_done = 1.0 - batch[SampleBatch.DONES].astype(jnp.float32)

        next_a = self.model.apply({"params": target_params}, nobs,
                                  method=_DDPGNets.pi)
        if cfg.get("smooth_target_policy", False):
            # TD3 target smoothing: clipped noise on the target action
            noise = jnp.clip(
                jax.random.normal(rng, next_a.shape)
                * cfg.get("target_noise", 0.2),
                -cfg.get("target_noise_clip", 0.5),
                cfg.get("target_noise_clip", 0.5))
            next_a = jnp.clip(next_a + noise, -1.0, 1.0)
        tq1, tq2 = self.model.apply({"params": target_params}, nobs,
                                    next_a, method=_DDPGNets.q)
        target_q = rews + gamma * not_done * jnp.minimum(tq1, tq2)
        target_q = jax.lax.stop_gradient(target_q)

        def critic_loss_fn(p):
            q1, q2 = self.model.apply({"params": p}, obs, acts,
                                      method=_DDPGNets.q)
            # importance weights from prioritized replay (Ape-X DDPG)
            w = batch.get("weights", jnp.ones_like(q1))
            loss = jnp.mean(w * (q1 - target_q) ** 2)
            if cfg.get("twin_q", False):
                loss = loss + jnp.mean(w * (q2 - target_q) ** 2)
            return loss, {"mean_q": jnp.mean(q1),
                          "mean_td_error": jnp.mean(
                              jnp.abs(q1 - target_q)),
                          # per-sample |TD| for priority updates
                          "td_errors": jnp.abs(q1 - target_q)}

        (loss_val, stats), grads = jax.value_and_grad(
            critic_loss_fn, has_aux=True)(params)
        updates, q_opt_state = self.q_optimizer.update(
            grads, q_opt_state, params)
        params = optax.apply_updates(params, updates)
        tau = cfg.get("tau", 0.005)
        target_params = jax.tree_util.tree_map(
            lambda t, p: (1 - tau) * t + tau * p, target_params, params)
        stats = dict(stats)
        stats["critic_loss"] = loss_val
        return params, target_params, q_opt_state, stats

    def _actor_update(self, params, pi_opt_state, batch):
        obs = batch[SampleBatch.OBS]

        def actor_loss_fn(p):
            a = self.model.apply({"params": p}, obs, method=_DDPGNets.pi)
            # gradient flows through the action into Q but must not move
            # the critic weights (same separation as SAC's actor term)
            frozen = jax.lax.stop_gradient(p)
            q1, _ = self.model.apply({"params": frozen}, obs, a,
                                     method=_DDPGNets.q)
            return -jnp.mean(q1)

        loss_val, grads = jax.value_and_grad(actor_loss_fn)(params)
        updates, pi_opt_state = self.pi_optimizer.update(
            grads, pi_opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, pi_opt_state, loss_val

    def learn_on_batch(self, batch) -> Dict[str, float]:
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                  if isinstance(v, np.ndarray) and v.dtype != object}
        (self.params, self.target_params, self.q_opt_state,
         stats) = self._jit_critic(self.params, self.target_params,
                                   self.q_opt_state, jbatch,
                                   self._next_rng())
        self._learn_steps += 1
        if self._learn_steps % self.config.get("policy_delay", 1) == 0:
            self.params, self.pi_opt_state, actor_loss = self._jit_actor(
                self.params, self.pi_opt_state, jbatch)
            stats = dict(stats)
            stats["actor_loss"] = actor_loss
        self.global_timestep += batch.count
        return _stats_to_host(stats)

    def value(self, obs):
        return np.zeros(len(obs), np.float32)

    def set_exploration(self, **attrs):
        for k, v in attrs.items():
            setattr(self, k, v)

    # ---- weights / state ----

    def get_weights(self):
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def get_state(self):
        return {"weights": self.get_weights(),
                "target": jax.device_get(self.target_params),
                "pi_opt_state": jax.device_get(self.pi_opt_state),
                "q_opt_state": jax.device_get(self.q_opt_state),
                "global_timestep": self.global_timestep,
                "learn_steps": self._learn_steps}

    def set_state(self, state):
        is_np = lambda x: isinstance(x, (np.ndarray, np.generic))
        self.set_weights(state["weights"])
        self.target_params = jax.tree_util.tree_map(
            jnp.asarray, state["target"])
        self.pi_opt_state = jax.tree_util.tree_map(
            jnp.asarray, state["pi_opt_state"], is_leaf=is_np)
        self.q_opt_state = jax.tree_util.tree_map(
            jnp.asarray, state["q_opt_state"], is_leaf=is_np)
        self.global_timestep = state.get("global_timestep", 0)
        self._learn_steps = state.get("learn_steps", 0)


class DDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self._config.update({
            "actor_lr": 1e-3,
            "critic_lr": 1e-3,
            "tau": 0.005,
            "twin_q": False,
            "policy_delay": 1,
            "smooth_target_policy": False,
            "target_noise": 0.2,
            "target_noise_clip": 0.5,
            "exploration_noise": 0.1,
            "replay_buffer_capacity": 100_000,
            "learning_starts": 256,
            "train_batch_size": 256,
            "rollout_fragment_length": 1,
            "training_intensity": 1,
        })


class TD3Config(DDPGConfig):
    """TD3 = DDPG + twin critics + delayed actor + target smoothing
    (reference: rllib/algorithms/td3.py)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or TD3)
        self._config.update({
            "twin_q": True,
            "policy_delay": 2,
            "smooth_target_policy": True,
        })


class DDPG(Algorithm):
    _policy_cls = DDPGPolicy
    _default_config_cls = DDPGConfig

    def setup(self, config):
        super().setup(config)
        self.replay = ReplayBuffer(
            self.config["replay_buffer_capacity"],
            seed=self.config.get("seed"))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        policy = self.workers.local_worker.policy
        batch = synchronous_parallel_sample(self.workers)
        self._timesteps_total += batch.count
        self.replay.add(batch)
        stats: Dict[str, float] = {}
        if len(self.replay) >= cfg["learning_starts"]:
            for _ in range(max(1, cfg.get("training_intensity", 1))):
                stats = policy.learn_on_batch(
                    self.replay.sample(cfg["train_batch_size"]))
            self.workers.sync_weights()
        stats.pop("td_errors", None)
        return {"num_env_steps_sampled_this_iter": batch.count,
                "replay_size": len(self.replay),
                **{f"learner/{k}": v for k, v in stats.items()}}


class TD3(DDPG):
    _default_config_cls = TD3Config
