"""PPO — proximal policy optimization with a jitted clipped-surrogate loss.

Reference analogue: rllib/algorithms/ppo/ppo.py:286 (training_step :311)
and ppo_torch_policy.py (loss). TPU-first: the whole
loss→grad→clip→adam-update is ONE compiled XLA program with donated
state; epochs × minibatches re-enter the same executable (fixed shapes cut
by ``SampleBatch.minibatches``).
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.rollout_worker import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import SampleBatch


class PPOPolicy(JaxPolicy):
    def postprocess_trajectory(self, batch):
        from ray_tpu.rllib.postprocessing import \
            compute_gae_for_sample_batch
        return compute_gae_for_sample_batch(
            self, batch, self.config.get("gamma", 0.99),
            self.config.get("lambda", 0.95))

    def loss(self, params, batch):
        cfg = self.config
        # rows added by SampleBatch.pad_to carry zero weight
        mask = batch.get("_valid_mask")
        if mask is None:
            mask = jnp.ones_like(batch[SampleBatch.ACTION_LOGP])
        denom = jnp.maximum(mask.sum(), 1.0)

        def mmean(x):
            return jnp.sum(x * mask) / denom

        dist_inputs, vf = self.model.apply(
            {"params": params}, batch[SampleBatch.OBS])
        logp = self.dist_logp(dist_inputs, batch[SampleBatch.ACTIONS])
        old_logp = batch[SampleBatch.ACTION_LOGP]
        adv = batch[SampleBatch.ADVANTAGES]
        adv_mean = mmean(adv)
        adv_std = jnp.sqrt(jnp.maximum(mmean((adv - adv_mean) ** 2), 0.0))
        adv = (adv - adv_mean) / (adv_std + 1e-8)
        ratio = jnp.exp(logp - old_logp)
        clip = cfg.get("clip_param", 0.3)
        surrogate = jnp.minimum(
            adv * ratio,
            adv * jnp.clip(ratio, 1.0 - clip, 1.0 + clip))
        # value clipping: squared error clamped at vf_clip_param, as the
        # reference torch policy does (ppo_torch_policy.py)
        vf_clip = cfg.get("vf_clip_param", 10.0)
        targets = batch[SampleBatch.VALUE_TARGETS]
        vf_err = jnp.clip((vf - targets) ** 2, 0.0, vf_clip)
        entropy = self.dist_entropy(dist_inputs)
        # approximate KL against the behavior logp for reporting/early stop
        kl = mmean(old_logp - logp)
        total = mmean(
            -surrogate
            + cfg.get("vf_loss_coeff", 1.0) * vf_err
            - cfg.get("entropy_coeff", 0.0) * entropy)
        return total, {
            "policy_loss": -mmean(surrogate),
            "vf_loss": mmean(vf_err),
            "entropy": mmean(entropy),
            "kl": kl,
        }


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self._config.update({
            "lr": 3e-4,
            "lambda": 0.95,
            "clip_param": 0.3,
            "vf_clip_param": 10.0,
            "vf_loss_coeff": 1.0,
            "entropy_coeff": 0.0,
            "num_sgd_iter": 10,
            "sgd_minibatch_size": 128,
            "train_batch_size": 4000,
            "grad_clip": None,
            "kl_target": 0.01,
        })


class PPO(Algorithm):
    _policy_cls = PPOPolicy
    _default_config_cls = PPOConfig

    def _sgd_epochs(self, policy, batch) -> Dict[str, float]:
        cfg = self.config
        rng = np.random.default_rng(cfg.get("seed", 0) + self._iteration)
        mb = cfg["sgd_minibatch_size"]
        if batch.count < mb:
            # padded rows carry _valid_mask=0 and are ignored by the loss
            batch = batch.pad_to(mb)
        stats: Dict[str, float] = {}
        for _ in range(cfg["num_sgd_iter"]):
            for minibatch in batch.minibatches(mb, rng=rng):
                stats = policy.learn_on_batch(minibatch)
        return stats

    def training_step(self) -> Dict[str, Any]:
        from ray_tpu.rllib.sample_batch import MultiAgentBatch
        cfg = self.config
        # 1. sample (reference: ppo.py:318 synchronous_parallel_sample)
        train_batch = synchronous_parallel_sample(
            self.workers, max_env_steps=cfg["train_batch_size"])
        sampled_steps = train_batch.count
        self._timesteps_total += sampled_steps
        lw = self.workers.local_worker
        # 2. minibatch SGD epochs on the local (learner) policy/policies
        if isinstance(train_batch, MultiAgentBatch):
            to_train = getattr(lw, "policies_to_train", None) or \
                list(lw.policy_map)
            learner_info: Dict[str, Dict[str, float]] = {}
            for pid in to_train:
                b = train_batch.policy_batches.get(pid)
                if b is None or b.count == 0:
                    continue
                learner_info[pid] = self._sgd_epochs(lw.policy_map[pid], b)
            flat = {f"learner/{pid}/{k}": v
                    for pid, st in learner_info.items()
                    for k, v in st.items()}
            self.workers.sync_weights()
            return {"num_env_steps_sampled_this_iter": sampled_steps,
                    "info": {"learner": learner_info}, **flat}
        stats = self._sgd_epochs(lw.policy, train_batch)
        # 3. broadcast new weights to rollout workers (ppo.py:345)
        self.workers.sync_weights()
        return {
            "num_env_steps_sampled_this_iter": sampled_steps,
            "info": {"learner": {"default_policy": stats}},
            **{f"learner/{k}": v for k, v in stats.items()},
        }
