"""DDPPO — decentralized distributed PPO.

Reference analogue: rllib/algorithms/ddppo/ddppo.py: rollout workers
compute AND apply the SGD updates locally (torch DDP allreduce between
workers); the driver only coordinates — sample batches and gradients
never ship through it.

TPU-native redesign: each worker runs the jitted PPO minibatch epochs on
its own samples worker-side (``worker.apply``), then the driver
parameter-averages the resulting weights and broadcasts — local-SGD
semantics (equal to gradient allreduce when num_sgd_iter=1, a trusted
approximation above). On a real pod the average would ride an ICI psum
via a collective group; through the object store it is one reduce at the
driver, which is still O(model), not O(batch).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig


def _local_sgd(worker, num_sgd_iter, minibatch_size, seed):
    """Sample + full PPO minibatch-SGD epochs, all inside the worker."""
    batch = worker.sample()
    policy = worker.policy
    if batch.count < minibatch_size:
        batch = batch.pad_to(minibatch_size)
    rng = np.random.default_rng(seed)
    stats: Dict[str, float] = {}
    for _ in range(num_sgd_iter):
        for mb in batch.minibatches(minibatch_size, rng=rng):
            stats = policy.learn_on_batch(mb)
    return policy.get_weights(), stats, batch.count


class DDPPOConfig(PPOConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPPO)
        self._config.update({
            "num_workers": 2,
            "num_sgd_iter": 5,
            "sgd_minibatch_size": 64,
            "rollout_fragment_length": 100,
        })


class DDPPO(PPO):
    _default_config_cls = DDPPOConfig

    def setup(self, config):
        super().setup(config)
        if not self.workers.remote_workers:
            raise ValueError("DDPPO requires num_workers >= 1 "
                             "(its point is decentralized learning)")

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        workers = self.workers.remote_workers
        seed = (cfg.get("seed") or 0) * 100_003 + self._iteration
        outs = ray_tpu.get([
            w.apply.remote(_local_sgd, cfg["num_sgd_iter"],
                           cfg["sgd_minibatch_size"], seed + i)
            for i, w in enumerate(workers)])
        weights = [o[0] for o in outs]
        # average scalar stats across replicas so one diverging worker
        # (e.g. NaN loss) is visible in the report
        stats = {k: float(np.mean([o[1][k] for o in outs]))
                 for k in outs[0][1]}
        sampled = sum(o[2] for o in outs)
        self._timesteps_total += sampled
        # the "allreduce": parameter average across workers
        avg = jax.tree_util.tree_map(
            lambda *xs: np.mean(np.stack(xs), axis=0), *weights)
        self.workers.local_worker.policy.set_weights(avg)
        self.workers.sync_weights()
        return {
            "num_env_steps_sampled_this_iter": sampled,
            "num_ddppo_workers": len(workers),
            **{f"learner/{k}": v for k, v in stats.items()},
        }
