"""MAML — model-agnostic meta-learning for RL (meta-gradients).

Reference analogue: rllib/algorithms/maml/ (maml.py, maml_torch_policy.py;
Finn et al. 2017): train initial policy parameters such that ONE inner
policy-gradient step on a new task's data yields a good task policy.
The meta-gradient differentiates THROUGH the inner update — in jax this
is literally ``jax.grad`` of (adapt ∘ surrogate), second-order terms
included, one jitted program per meta-update. The task family is 2D
point navigation with per-task goals (reference analogue:
rllib/examples/env/pointmass / the MAML paper's point environment).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm


class PointGoalEnv:
    """2D point mass navigating to a per-task goal on the unit circle.
    Reward = -distance to goal; the task (goal) is resampled by
    ``sample_task``/``set_task`` — the MAML adaptation axis."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        cfg = config or {}
        self.horizon = int(cfg.get("horizon", 20))
        self.action_scale = float(cfg.get("action_scale", 0.25))
        self.goal = np.array([1.0, 0.0], np.float32)
        self._pos = np.zeros(2, np.float32)
        self._t = 0

    def sample_task(self, rng: np.random.Generator) -> np.ndarray:
        theta = rng.uniform(0, 2 * np.pi)
        return np.array([np.cos(theta), np.sin(theta)], np.float32)

    def set_task(self, goal: np.ndarray):
        self.goal = np.asarray(goal, np.float32)

    def reset(self, *, seed=None):
        self._pos = np.zeros(2, np.float32)
        self._t = 0
        return self._pos.copy(), {}

    def step(self, action):
        a = np.clip(np.asarray(action, np.float32), -1.0, 1.0)
        # bounded arena: keeps the reward scale sane under exploratory
        # policies (unbounded drift would dominate the meta-objective)
        self._pos = np.clip(self._pos + self.action_scale * a, -2.0, 2.0)
        self._t += 1
        r = -float(np.linalg.norm(self._pos - self.goal))
        return self._pos.copy(), r, False, self._t >= self.horizon, {}


class _GaussianPolicy(nn.Module):
    """Fixed-std Gaussian: MAML adapts the mean net. A learnable std
    under a pure REINFORCE meta-objective inflates without a KL
    constraint (the reference stabilizes with TRPO); fixing it keeps
    the one-jitted-program meta-update stable."""
    act_dim: int
    hidden: int = 64
    fixed_std: float = 0.3

    @nn.compact
    def __call__(self, obs):
        x = nn.tanh(nn.Dense(self.hidden)(obs))
        x = nn.tanh(nn.Dense(self.hidden)(x))
        mean = nn.Dense(self.act_dim)(x)
        logstd = jnp.full_like(mean, jnp.log(self.fixed_std))
        return mean, logstd


class MAMLConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MAML)
        self._config.update({
            "env": "point_goal",
            "env_config": {},
            "inner_lr": 0.1,
            "lr": 1e-3,             # meta (outer) lr
            "meta_batch_size": 10,  # tasks per meta-update
            "episodes_per_task": 10,
            "inner_adaptation_steps": 1,
            "hidden": 64,
        })


class MAML(LocalAlgorithm):
    """MAML meta-RL (reference: maml.py training_step — sample tasks,
    inner adapt per task, outer update through the adaptation)."""

    _default_config_cls = MAMLConfig

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        if cfg["env"] != "point_goal":
            raise ValueError("MAML ships the point_goal task family")
        self.env = PointGoalEnv(cfg.get("env_config"))
        self.obs_dim, self.act_dim = 2, 2
        self.policy = _GaussianPolicy(self.act_dim, cfg["hidden"])
        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        self.params = self.policy.init(
            self._rng, jnp.zeros((1, self.obs_dim)))["params"]
        self.target_params = self.params  # checkpoint symmetry
        self.optimizer = optax.adam(cfg["lr"])
        self.opt_state = self.optimizer.init(self.params)

        def act_impl(params, obs, key):
            mean, logstd = self.policy.apply({"params": params}, obs)
            eps = jax.random.normal(key, mean.shape)
            return mean + jnp.exp(logstd) * eps

        self._jit_act = jax.jit(act_impl)
        self._jit_adapt = jax.jit(self._adapt_impl)
        self._jit_meta = jax.jit(self._meta_impl)
        self._init_local_state()

    # ---- surrogate / adaptation (pure jax; meta-grad flows through) ----

    def _logp(self, params, obs, act):
        mean, logstd = self.policy.apply({"params": params}, obs)
        var = jnp.exp(2 * logstd)
        return jnp.sum(
            -0.5 * ((act - mean) ** 2 / var) - logstd
            - 0.5 * jnp.log(2 * jnp.pi), axis=-1)

    def _surrogate(self, params, batch):
        # advantages are pre-baselined PER TIMESTEP across the task's
        # episodes (a global mean over returns-to-go manufactures a
        # time-index signal: early steps always carry lower rtg)
        adv = batch["advantages"]
        adv = adv / (jnp.std(adv) + 1e-6)
        return -jnp.mean(
            self._logp(params, batch["obs"], batch["actions"]) * adv)

    def _adapt_impl(self, params, batch):
        """One (or more) inner policy-gradient steps."""
        lr = self.config["inner_lr"]
        for _ in range(self.config["inner_adaptation_steps"]):
            grads = jax.grad(self._surrogate)(params, batch)
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads)
        return params

    def _meta_impl(self, params, opt_state, pre_batches, post_batches):
        """Meta-gradient: d/dθ Σ_tasks surrogate(adapt(θ, pre), post) —
        jax.grad through _adapt_impl carries the second-order terms
        (reference: maml_torch_policy.py MAMLLoss create_graph=True)."""

        def outer_loss(p):
            losses = [
                self._surrogate(self._adapt_impl(p, pre), post)
                for pre, post in zip(pre_batches, post_batches)]
            return jnp.mean(jnp.stack(losses))

        loss, grads = jax.value_and_grad(outer_loss)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        return (optax.apply_updates(params, updates), opt_state,
                {"meta_loss": loss,
                 "grad_norm": optax.global_norm(grads)})

    # ---- rollouts ----

    def _next_key(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _collect_task(self, params, goal) -> Tuple[Dict[str, jnp.ndarray],
                                                   float]:
        """episodes_per_task rollouts on one task; returns the batch
        (obs/actions/returns-to-go) and the mean episode reward."""
        cfg = self.config
        self.env.set_task(goal)
        all_obs, all_act, all_rtg, ep_rewards = [], [], [], []
        for _ in range(cfg["episodes_per_task"]):
            obs, _ = self.env.reset()
            o_l, a_l, r_l = [], [], []
            done = False
            while not done:
                a = np.asarray(self._jit_act(
                    params, jnp.asarray(obs[None]), self._next_key()))[0]
                nobs, r, term, trunc, _ = self.env.step(a)
                o_l.append(obs)
                a_l.append(a)
                r_l.append(r)
                obs, done = nobs, (term or trunc)
            ep_rewards.append(float(np.sum(r_l)))
            all_obs.append(np.stack(o_l))
            all_act.append(np.stack(a_l))
            all_rtg.append(
                np.cumsum(np.asarray(r_l, np.float32)[::-1])[::-1])
        rtg = np.stack(all_rtg)                    # (E, T)
        adv = rtg - rtg.mean(axis=0, keepdims=True)  # per-timestep baseline
        batch = {
            "obs": jnp.asarray(np.concatenate(all_obs)),
            "actions": jnp.asarray(np.concatenate(all_act)),
            "advantages": jnp.asarray(adv.reshape(-1)),
        }
        return batch, float(np.mean(ep_rewards))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        pre_batches, post_batches = [], []
        pre_rewards, post_rewards = [], []
        n = 0
        for _ in range(cfg["meta_batch_size"]):
            goal = self.env.sample_task(self._np_rng)
            pre, pre_rw = self._collect_task(self.params, goal)
            adapted = self._jit_adapt(self.params, pre)
            post, post_rw = self._collect_task(adapted, goal)
            pre_batches.append(pre)
            post_batches.append(post)
            pre_rewards.append(pre_rw)
            post_rewards.append(post_rw)
            n += int(pre["obs"].shape[0] + post["obs"].shape[0])
        self.params, self.opt_state, jstats = self._jit_meta(
            self.params, self.opt_state, pre_batches, post_batches)
        self._timesteps_total += n
        post_mean = float(np.mean(post_rewards))
        self._episode_reward_window.append(post_mean)
        return {
            "num_env_steps_sampled_this_iter": n,
            "pre_adaptation_reward_mean": float(np.mean(pre_rewards)),
            "post_adaptation_reward_mean": post_mean,
            "adaptation_gap": post_mean - float(np.mean(pre_rewards)),
            **{f"learner/{k}": float(v) for k, v in jstats.items()},
        }

    def adaptation_eval(self, num_tasks: int = 8,
                        seed: int = 500) -> Dict[str, float]:
        """Pre- vs post-adaptation reward on held-out tasks."""
        rng = np.random.default_rng(seed)
        pre_rw, post_rw = [], []
        for _ in range(num_tasks):
            goal = self.env.sample_task(rng)
            pre, prw = self._collect_task(self.params, goal)
            adapted = self._jit_adapt(self.params, pre)
            _, porw = self._collect_task(adapted, goal)
            pre_rw.append(prw)
            post_rw.append(porw)
        return {"pre_adaptation_reward": float(np.mean(pre_rw)),
                "post_adaptation_reward": float(np.mean(post_rw))}
