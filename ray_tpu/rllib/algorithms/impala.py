"""IMPALA — importance-weighted actor-learner with V-trace.

Reference analogue: rllib/algorithms/impala/ (+ vtrace_torch.py, async
learner queues in execution/learner_thread.py). TPU-first shape: actors
sample asynchronously (futures held open per worker, reaped with
``ray_tpu.wait``); the learner runs one jitted program in which V-trace is
a ``lax.scan`` in reverse over the (time-ordered) batch, cut at episode /
fragment boundaries — no Python loop touches the device path.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.sample_batch import SampleBatch


def vtrace_scan(behaviour_logp, target_logp, rewards, values, next_values,
                terms, cuts, gamma, clip_rho=1.0, clip_c=1.0):
    """V-trace targets over a flat time-ordered sequence.

    ``cuts`` marks the last row of each contiguous fragment (episode end or
    truncation) — the reverse accumulator resets there, and ``next_values``
    supplies the bootstrap. Pure function, safe under jit.
    """
    rho = jnp.minimum(jnp.exp(target_logp - behaviour_logp), clip_rho)
    c = jnp.minimum(jnp.exp(target_logp - behaviour_logp), clip_c)
    not_term = 1.0 - terms
    deltas = rho * (rewards + gamma * not_term * next_values - values)
    cont = gamma * (1.0 - cuts)

    def backward(acc, xs):
        delta, c_t, cont_t = xs
        acc = delta + cont_t * c_t * acc
        return acc, acc

    _, acc = jax.lax.scan(backward, jnp.float32(0.0),
                          (deltas, c, cont), reverse=True)
    vs = values + acc
    # vs_{t+1}: within a fragment use the next row's vs; at cuts fall back
    # to the bootstrap value.
    vs_next = jnp.concatenate([vs[1:], next_values[-1:]])
    vs_next = jnp.where(cuts > 0, next_values, vs_next)
    pg_adv = rho * (rewards + gamma * not_term * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class IMPALAPolicy(JaxPolicy):
    def _vtrace_terms(self, params, batch):
        """Shared V-trace computation: (dist_inputs, values, target_logp,
        vs, pg_adv). Subclasses (APPO) swap only the surrogate term."""
        cfg = self.config
        dist_inputs, values = self.model.apply(
            {"params": params}, batch[SampleBatch.OBS])
        _, next_values = self.model.apply(
            {"params": params}, batch[SampleBatch.NEXT_OBS])
        next_values = jax.lax.stop_gradient(next_values)
        target_logp = self.dist_logp(dist_inputs,
                                     batch[SampleBatch.ACTIONS])
        vs, pg_adv = vtrace_scan(
            batch[SampleBatch.ACTION_LOGP], target_logp,
            batch[SampleBatch.REWARDS], jax.lax.stop_gradient(values),
            next_values,
            batch[SampleBatch.DONES].astype(jnp.float32),
            batch["cuts"].astype(jnp.float32),
            cfg.get("gamma", 0.99),
            clip_rho=cfg.get("vtrace_clip_rho_threshold", 1.0),
            clip_c=cfg.get("vtrace_clip_c_threshold", 1.0))
        return dist_inputs, values, target_logp, vs, pg_adv

    def _assemble_loss(self, pg_loss, dist_inputs, values, vs):
        cfg = self.config
        vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
        entropy = jnp.mean(self.dist_entropy(dist_inputs))
        total = (pg_loss
                 + cfg.get("vf_loss_coeff", 0.5) * vf_loss
                 - cfg.get("entropy_coeff", 0.01) * entropy)
        return total, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    def loss(self, params, batch):
        dist_inputs, values, target_logp, vs, pg_adv = \
            self._vtrace_terms(params, batch)
        pg_loss = -jnp.mean(target_logp * pg_adv)
        total, stats = self._assemble_loss(pg_loss, dist_inputs, values,
                                           vs)
        stats["mean_vtrace_adv"] = jnp.mean(pg_adv)
        return total, stats


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self._config.update({
            "lr": 5e-4,
            "rollout_fragment_length": 50,
            "vf_loss_coeff": 0.5,
            "entropy_coeff": 0.01,
            "vtrace_clip_rho_threshold": 1.0,
            "vtrace_clip_c_threshold": 1.0,
            "grad_clip": 40.0,
            "num_workers": 1,
            "broadcast_interval": 1,
            "max_sample_batches_per_iter": 8,
            # decoupled learner thread (the defining IMPALA structure);
            # False falls back to learn-inline-with-reaping
            "async_learner": True,
            "learner_queue_size": 16,
        })


def _mark_cuts(batch: SampleBatch) -> SampleBatch:
    """Add the 'cuts' column: 1 on the last row of every contiguous
    per-episode fragment."""
    cuts = np.zeros(batch.count, np.float32)
    offset = 0
    for frag in batch.split_by_episode():
        offset += frag.count
        cuts[offset - 1] = 1.0
    batch["cuts"] = cuts
    return batch


class IMPALA(Algorithm):
    _policy_cls = IMPALAPolicy
    _default_config_cls = IMPALAConfig

    def setup(self, config):
        super().setup(config)
        self._in_flight: Dict[Any, Any] = {}  # future -> worker
        self._learn_count = 0
        self._learner = None
        if self.config.get("async_learner", True) and \
                self.workers.remote_workers:
            from ray_tpu.rllib.execution import LearnerThread
            self._learner = LearnerThread(
                self.workers.local_worker.policy,
                max_queue_size=self.config.get("learner_queue_size", 16))
            self._learner.start()

    def _launch(self, worker):
        fut = worker.sample.remote()
        self._in_flight[fut] = worker

    def _broadcast_weights(self, worker):
        if self._learner is not None:
            weights = self._learner.get_weights()
        else:
            weights = self.workers.local_worker.policy.get_weights()
        worker.set_weights.remote(ray_tpu.put(weights))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        policy = self.workers.local_worker.policy
        stats: Dict[str, float] = {}
        sampled = 0
        if not self.workers.remote_workers:
            # degenerate sync path (num_workers=0)
            batch = _mark_cuts(self.workers.local_worker.sample())
            stats = policy.learn_on_batch(batch)
            sampled = batch.count
            self._timesteps_total += sampled
            return {
                "num_env_steps_sampled_this_iter": sampled,
                **{f"learner/{k}": v for k, v in stats.items()},
            }

        for w in self.workers.remote_workers:
            if w not in self._in_flight.values():
                self._launch(w)
        n_target = cfg.get("max_sample_batches_per_iter", 8)
        reaped = 0
        while reaped < n_target:
            ready, _ = ray_tpu.wait(list(self._in_flight),
                                    num_returns=1, timeout=60.0)
            if not ready:
                break
            fut = ready[0]
            worker = self._in_flight.pop(fut)
            batch = _mark_cuts(ray_tpu.get(fut))
            if self._learner is not None:
                # decoupled: enqueue and keep reaping — sampling overlaps
                # the device update. A full queue applies backpressure by
                # blocking here until the learner drains (dropping the
                # batch would silently lose experience while still
                # counting it as trained).
                while not self._learner.put(batch, timeout=5.0):
                    self._learner.check_error()
            else:
                stats = policy.learn_on_batch(batch)
            sampled += batch.count
            self._learn_count += 1
            if self._learn_count % cfg.get("broadcast_interval", 1) == 0:
                self._broadcast_weights(worker)
            self._launch(worker)
            reaped += 1
        if self._learner is not None:
            self._learner.check_error()
            stats = dict(self._learner.stats)
            stats.update(self._learner.metrics())
        self._timesteps_total += sampled
        return {
            "num_env_steps_sampled_this_iter": sampled,
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def cleanup(self):
        if self._learner is not None:
            self._learner.stop()
        self._in_flight.clear()
        super().cleanup()
