"""Contextual bandits — LinUCB and linear Thompson sampling.

Reference analogue: rllib/algorithms/bandit/ (bandit.py,
bandit_torch_policy.py backed by models/torch/modules/bandits — exact
ridge-regression per arm, no SGD) plus the example envs in
rllib/examples/env/bandit_envs_discrete.py. The per-arm sufficient
statistics (A = I + Σ x xᵀ, b = Σ r x) update exactly per observed
reward; exploration is the UCB bonus or a posterior sample. Host-side
numpy by design: these are tiny dense solves where an accelerator
round-trip would dominate.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import Box, Discrete
from ray_tpu.rllib.rollout_worker import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import SampleBatch


class LinearDiscreteBanditEnv:
    """K-arm contextual bandit with hidden linear payoffs: context
    x ~ N(0, I_d), reward(a) = x·w_a + noise; 1-step episodes
    (reference: examples/env/bandit_envs_discrete.py)."""

    def __init__(self, config: Dict[str, Any] = None):
        config = config or {}
        d = config.get("feature_dim", 8)
        k = config.get("num_arms", 4)
        rng = np.random.default_rng(config.get("payoff_seed", 7))
        self._w = rng.normal(size=(k, d)).astype(np.float32)
        self._noise = config.get("noise_std", 0.1)
        self._rng = np.random.default_rng(config.get("seed"))
        self.observation_space = Box(-np.inf, np.inf, (d,))
        self.action_space = Discrete(k)
        self._x = None

    def best_expected_reward(self, x) -> float:
        return float(np.max(self._w @ x))

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._x = self._rng.normal(
            size=self._w.shape[1]).astype(np.float32)
        return self._x, {}

    def step(self, action):
        r = float(self._w[int(action)] @ self._x
                  + self._rng.normal(0, self._noise))
        obs = self._x
        self._x = None
        return obs, r, True, False, {}


class LinUCBPolicy:
    """Per-arm ridge regression + UCB bonus (Li et al. 2010)."""

    def __init__(self, obs_space, action_space, config: Dict[str, Any]):
        assert isinstance(action_space, Discrete), \
            "bandit policies need a Discrete arm space"
        self.observation_space = obs_space
        self.action_space = action_space
        self.config = config
        self.d = int(np.prod(obs_space.shape))
        self.k = action_space.n
        lam = config.get("ridge_lambda", 1.0)
        self.A = np.stack([np.eye(self.d, dtype=np.float64) * lam
                           for _ in range(self.k)])
        self.b = np.zeros((self.k, self.d), np.float64)
        self.alpha = config.get("ucb_alpha", 1.0)
        self._rng = np.random.default_rng(config.get("seed"))
        self.global_timestep = 0

    def _posterior(self):
        """Per-arm (A⁻¹, θ̂ = A⁻¹b) — shared by UCB and TS scoring."""
        inv = np.linalg.inv(self.A)            # (K, d, d)
        theta = np.einsum("kde,ke->kd", inv, self.b)
        return inv, theta

    # scoring, overridden by Thompson sampling
    def _scores(self, x: np.ndarray, explore: bool) -> np.ndarray:
        """x: (B, d) → (B, K) acquisition scores."""
        inv, theta = self._posterior()
        mean = x @ theta.T                     # (B, K)
        if not explore:
            return mean
        var = np.einsum("bd,kde,be->bk", x, inv, x)
        return mean + self.alpha * np.sqrt(np.maximum(var, 0.0))

    def compute_actions(self, obs, explore=True):
        x = np.asarray(obs, np.float64).reshape(len(obs), -1)
        actions = np.argmax(self._scores(x, explore), axis=-1)
        n = len(actions)
        extras = {
            SampleBatch.ACTION_LOGP: np.zeros(n, np.float32),
            SampleBatch.ACTION_DIST_INPUTS: np.zeros((n, self.k),
                                                     np.float32),
            SampleBatch.VF_PREDS: np.zeros(n, np.float32),
        }
        return actions.astype(np.int64), extras

    def postprocess_trajectory(self, batch):
        return batch

    def learn_on_batch(self, batch) -> Dict[str, float]:
        x = np.asarray(batch[SampleBatch.OBS],
                       np.float64).reshape(batch.count, -1)
        acts = np.asarray(batch[SampleBatch.ACTIONS], np.int64)
        rews = np.asarray(batch[SampleBatch.REWARDS], np.float64)
        for xi, ai, ri in zip(x, acts, rews):
            self.A[ai] += np.outer(xi, xi)
            self.b[ai] += ri * xi
        self.global_timestep += batch.count
        return {"mean_reward": float(rews.mean()),
                "arms_pulled": float(len(np.unique(acts)))}

    def value(self, obs):
        return np.zeros(len(obs), np.float32)

    def get_weights(self):
        return {"A": self.A.copy(), "b": self.b.copy()}

    def set_weights(self, weights):
        self.A = np.asarray(weights["A"], np.float64).copy()
        self.b = np.asarray(weights["b"], np.float64).copy()

    def get_state(self):
        return {"weights": self.get_weights(),
                "global_timestep": self.global_timestep}

    def set_state(self, state):
        self.set_weights(state["weights"])
        self.global_timestep = state.get("global_timestep", 0)


class LinTSPolicy(LinUCBPolicy):
    """Linear Thompson sampling: score by a posterior draw
    θ̃_k ~ N(A⁻¹b, v²A⁻¹) (reference: bandit_torch_model.py
    DiscreteLinearModelThompsonSampling)."""

    def _scores(self, x, explore):
        inv, theta = self._posterior()
        if not explore:
            return x @ theta.T
        v = self.config.get("ts_v", 0.5)
        draws = np.stack([
            self._rng.multivariate_normal(theta[k], v * v * inv[k])
            for k in range(self.k)])
        return x @ draws.T


class BanditLinUCBConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BanditLinUCB)
        self._config.update({
            "env": LinearDiscreteBanditEnv,
            "rollout_fragment_length": 32,
            "train_batch_size": 32,
            "ucb_alpha": 1.0,
            "ridge_lambda": 1.0,
        })


class BanditLinTSConfig(BanditLinUCBConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BanditLinTS)
        self._config.update({"ts_v": 0.5})


class BanditLinUCB(Algorithm):
    _policy_cls = LinUCBPolicy
    _default_config_cls = BanditLinUCBConfig

    def training_step(self) -> Dict[str, Any]:
        batch = synchronous_parallel_sample(
            self.workers, max_env_steps=self.config["train_batch_size"])
        self._timesteps_total += batch.count
        stats = self.workers.local_worker.policy.learn_on_batch(batch)
        self.workers.sync_weights()
        return {"num_env_steps_sampled_this_iter": batch.count,
                **{f"learner/{k}": v for k, v in stats.items()}}


class BanditLinTS(BanditLinUCB):
    _policy_cls = LinTSPolicy
    _default_config_cls = BanditLinTSConfig
