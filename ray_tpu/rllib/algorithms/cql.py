"""CQL — conservative Q-learning for offline RL.

Reference analogue: rllib/algorithms/cql/ (cql.py, cql_torch_policy.py):
SAC's actor/critic/alpha machinery plus a conservative penalty on the
critic — logsumexp over sampled actions (uniform + policy, with
importance corrections) minus the dataset Q — and an initial
behavior-cloning phase for the actor (``bc_iters``). Trains purely from
a JsonReader dataset; the env is used only for evaluation.

The whole update (SAC core + penalty, both phases) is ONE jitted
program: the BC→SAC actor switch is a traced scalar weight, not a
Python branch, so the executable never recompiles mid-training.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithms.sac import (SAC, SACConfig, SACPolicy,
                                          _SACNets, _dataset_action_logp,
                                          _squash)
from ray_tpu.rllib.offline import (OfflineAlgorithmMixin,
                                   OfflineDataConfigMixin)
from ray_tpu.rllib.sample_batch import SampleBatch


class CQLPolicy(SACPolicy):
    def _q_many(self, params, obs, acts):
        """Q(s, a_i) for N action samples: (N, B, d) -> two (N, B)."""
        n, b, d = acts.shape
        obs_rep = jnp.broadcast_to(obs[None], (n, b, obs.shape[-1]))
        q1, q2 = self.model.apply(
            {"params": params}, obs_rep.reshape(n * b, -1),
            acts.reshape(n * b, d), method=_SACNets.q)
        return q1.reshape(n, b), q2.reshape(n, b)

    def _update_impl(self, params, target_params, log_alpha, opt_state,
                     batch, rng):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        alpha_cql = cfg.get("cql_alpha", 1.0)
        n_samp = cfg.get("cql_num_actions", 4)
        target_entropy = -float(self.act_dim)
        obs = batch[SampleBatch.OBS]
        nobs = batch[SampleBatch.NEXT_OBS]
        acts = batch["raw_actions"]
        rews = batch[SampleBatch.REWARDS]
        not_done = 1.0 - batch[SampleBatch.DONES].astype(jnp.float32)
        # 1.0 during the BC phase, 0.0 after (traced, no recompile)
        bc_w = batch["_bc_weight"]
        rngs = jax.random.split(rng, 5)

        # SAC target Q
        mean_n, log_std_n = self.model.apply(
            {"params": target_params}, nobs, method=_SACNets.pi)
        next_a, next_logp = _squash(mean_n, log_std_n, rngs[0])
        tq1, tq2 = self.model.apply({"params": target_params}, nobs,
                                    next_a, method=_SACNets.q)
        alpha = jnp.exp(log_alpha)
        target_q = rews + gamma * not_done * (
            jnp.minimum(tq1, tq2) - alpha * next_logp)
        target_q = jax.lax.stop_gradient(target_q)

        def sample_n(p, o, key):
            """(N, B) actions + logps from the current policy at obs o."""
            mean, log_std = self.model.apply({"params": p}, o,
                                             method=_SACNets.pi)
            def one(k):
                return _squash(mean, log_std, k)
            a, lp = jax.vmap(one)(jax.random.split(key, n_samp))
            return a, lp

        def loss_fn(trainables):
            p, la = trainables
            q1, q2 = self.model.apply({"params": p}, obs, acts,
                                      method=_SACNets.q)
            bellman = jnp.mean((q1 - target_q) ** 2
                               + (q2 - target_q) ** 2)

            # conservative penalty: logsumexp over uniform + policy +
            # next-policy actions with importance corrections
            # (cql_torch_policy.py; Kumar et al. Eq. 4 w/ IS)
            b = obs.shape[0]
            rand_a = jax.random.uniform(
                rngs[1], (n_samp, b, self.act_dim), minval=-1.0,
                maxval=1.0)
            pi_a, pi_logp = sample_n(p, obs, rngs[2])
            npi_a, npi_logp = sample_n(p, nobs, rngs[3])
            # the penalty trains the CRITIC only: block the
            # reparameterized path through the sampled actions, else
            # minimizing the penalty pushes the actor toward LOW-Q
            # actions (opposing the actor objective)
            pi_a = jax.lax.stop_gradient(pi_a)
            npi_a = jax.lax.stop_gradient(npi_a)
            rq1, rq2 = self._q_many(p, obs, rand_a)
            pq1, pq2 = self._q_many(p, obs, pi_a)
            nq1, nq2 = self._q_many(p, obs, npi_a)
            log_unif = -self.act_dim * jnp.log(2.0)  # density of U(-1,1)^d

            def cat_lse(rq, pq, nq):
                cat = jnp.concatenate([
                    rq - log_unif,
                    pq - jax.lax.stop_gradient(pi_logp),
                    nq - jax.lax.stop_gradient(npi_logp)], axis=0)
                return jax.scipy.special.logsumexp(
                    cat, axis=0) - jnp.log(3 * n_samp)

            penalty = (jnp.mean(cat_lse(rq1, pq1, nq1) - q1)
                       + jnp.mean(cat_lse(rq2, pq2, nq2) - q2))
            critic_loss = bellman + alpha_cql * penalty

            # actor: BC warmup cross-fading into the SAC objective
            mean, log_std = self.model.apply({"params": p}, obs,
                                             method=_SACNets.pi)
            new_a, new_logp = _squash(mean, log_std, rngs[4])
            frozen_p = jax.lax.stop_gradient(p)
            fq1, fq2 = self.model.apply({"params": frozen_p}, obs, new_a,
                                        method=_SACNets.q)
            sac_actor = jnp.mean(
                jnp.exp(jax.lax.stop_gradient(la)) * new_logp
                - jnp.minimum(fq1, fq2))
            data_logp = _dataset_action_logp(acts, mean, log_std)
            bc_actor = jnp.mean(
                jnp.exp(jax.lax.stop_gradient(la)) * new_logp - data_logp)
            actor_loss = bc_w * bc_actor + (1.0 - bc_w) * sac_actor

            alpha_loss = -jnp.mean(
                la * jax.lax.stop_gradient(new_logp + target_entropy))
            total = critic_loss + actor_loss + alpha_loss
            return total, {"critic_loss": critic_loss,
                           "bellman_loss": bellman,
                           "cql_penalty": penalty,
                           "actor_loss": actor_loss,
                           "alpha": jnp.exp(la),
                           "mean_q": jnp.mean(q1)}

        (loss_val, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)((params, log_alpha))
        updates, opt_state = self.optimizer.update(
            grads, opt_state, (params, log_alpha))
        params, log_alpha = optax.apply_updates((params, log_alpha),
                                                updates)
        tau = cfg.get("tau", 0.005)
        target_params = jax.tree_util.tree_map(
            lambda t, p: (1 - tau) * t + tau * p, target_params, params)
        stats = dict(stats)
        stats["total_loss"] = loss_val
        return params, target_params, log_alpha, opt_state, stats


class CQLConfig(OfflineDataConfigMixin, SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self._config.update({
            "input_path": None,
            "cql_alpha": 1.0,
            "cql_num_actions": 4,
            "bc_iters": 200,  # actor BC warmup learn-steps
            "train_batch_size": 256,
            "num_iters_per_step": 10,
        })


class CQL(OfflineAlgorithmMixin, Algorithm):
    _policy_cls = CQLPolicy
    _default_config_cls = CQLConfig

    def setup(self, config):
        super().setup(config)
        self._load_offline_dataset()
        self._learn_steps = 0

    def training_step(self) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        cfg = self.config
        bs = cfg["train_batch_size"]
        stats: Dict[str, float] = {}
        for _ in range(cfg.get("num_iters_per_step", 10)):
            mb = self._offline_minibatch(bs)
            mb["_bc_weight"] = np.full(
                (), 1.0 if self._learn_steps < cfg["bc_iters"] else 0.0,
                np.float32)
            stats = policy.learn_on_batch(mb)
            self._learn_steps += 1
            self._timesteps_total += bs
        self.workers.sync_weights()
        return {"num_env_steps_sampled_this_iter": 0,
                "learn_steps_total": self._learn_steps,
                **{f"learner/{k}": v for k, v in stats.items()}}
