"""AlphaStar-style league self-play training.

Reference analogue: rllib/algorithms/alpha_star/ (league-based
training: a LEAGUE of policies — main agents, main exploiters, league
exploiters, frozen historical snapshots — matched by prioritized
fictitious self-play over a payoff matrix; distributed_learners.py +
league_builder.py). The full game there is StarCraft; the
architecturally distinct machinery is the LEAGUE: PFSP matchmaking,
exploiter roles, periodic snapshotting, and a win-rate payoff table —
reproduced here on the in-repo two-player board games (alpha_zero.py's
TicTacToe/Connect4) with jitted REINFORCE-with-baseline updates per
learnable player. One process, jax-first: every learner shares one
network ARCHITECTURE (a pytree of params per player), so a single
jitted update function serves the whole league.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm
from ray_tpu.rllib.algorithms.alpha_zero import GAMES

MAIN = "main"
MAIN_EXPLOITER = "main_exploiter"
LEAGUE_EXPLOITER = "league_exploiter"
HISTORICAL = "historical"


class _PolicyNet(nn.Module):
    num_actions: int
    hidden: int

    @nn.compact
    def __call__(self, x):
        h = nn.relu(nn.Dense(self.hidden)(x))
        h = nn.relu(nn.Dense(self.hidden)(h))
        logits = nn.Dense(self.num_actions)(h)
        value = nn.Dense(1)(h)
        return logits, value[..., 0]


class LeaguePlayer:
    """One league member (reference: league_builder.py Player*)."""

    def __init__(self, pid: str, ptype: str, params):
        self.pid = pid
        self.ptype = ptype
        self.params = params
        self.games = 0

    @property
    def learnable(self) -> bool:
        return self.ptype != HISTORICAL


def pfsp_weights(win_rates: np.ndarray, mode: str = "squared"
                 ) -> np.ndarray:
    """Prioritized fictitious self-play opponent weighting (reference:
    alpha_star/league_builder.py pfsp): weight opponents the learner
    does NOT reliably beat. ``win_rates`` are the LEARNER's win rates
    vs each candidate."""
    p = np.clip(win_rates, 0.0, 1.0)
    if mode == "squared":
        w = (1.0 - p) ** 2
    elif mode == "variance":
        w = p * (1.0 - p)
    else:
        w = 1.0 - p
    w = w + 1e-3  # never fully starve an opponent
    return w / w.sum()


class AlphaStarConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or AlphaStar)
        self._config.update({
            "env": "tictactoe",
            "hidden": 64,
            "lr": 3e-3,
            "gamma": 1.0,
            "matches_per_iter": 64,
            "entropy_coeff": 0.01,
            "vf_coeff": 0.5,
            # league shape (reference defaults scaled to one process)
            "num_main_exploiters": 1,
            "num_league_exploiters": 1,
            "snapshot_interval": 10,   # iterations between main snapshots
            "max_historical": 8,
            # matchmaking mix for the main agent (reference: 35% SP /
            # 50% PFSP / 15% exploiter-targeting)
            "main_self_play_prob": 0.35,
            "payoff_ema": 0.05,
        })


class AlphaStar(LocalAlgorithm):
    """League training loop: sample matches by PFSP, update the
    learnable participant on each game, snapshot main periodically."""

    _default_config_cls = AlphaStarConfig

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        game_cls = GAMES.get(cfg["env"])
        if game_cls is None:
            raise ValueError(
                f"AlphaStar env must be one of {sorted(GAMES)}")
        self.game = game_cls()
        self.net = _PolicyNet(self.game.num_actions, cfg["hidden"])
        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        dummy = jnp.zeros((1,) + self.game.obs_shape)

        def fresh_params(key):
            return self.net.init(key, dummy)["params"]

        self.optimizer = optax.adam(cfg["lr"])
        # the league (reference: league_builder.py __init__): one main,
        # N main exploiters, M league exploiters; separate param sets
        self.league: Dict[str, LeaguePlayer] = {}
        keys = jax.random.split(self._rng, 2 + cfg["num_main_exploiters"]
                                + cfg["num_league_exploiters"])
        self.league[MAIN] = LeaguePlayer(MAIN, MAIN, fresh_params(keys[0]))
        for i in range(cfg["num_main_exploiters"]):
            pid = f"{MAIN_EXPLOITER}_{i}"
            self.league[pid] = LeaguePlayer(pid, MAIN_EXPLOITER,
                                            fresh_params(keys[1 + i]))
        for i in range(cfg["num_league_exploiters"]):
            pid = f"{LEAGUE_EXPLOITER}_{i}"
            self.league[pid] = LeaguePlayer(
                pid, LEAGUE_EXPLOITER,
                fresh_params(keys[1 + cfg["num_main_exploiters"] + i]))
        # payoff[a][b] = EMA win rate of a against b (reference: the
        # league's payoff matrix driving PFSP)
        self.payoff: Dict[str, Dict[str, float]] = {}
        self._opt_states: Dict[str, Any] = {
            pid: self.optimizer.init(p.params)
            for pid, p in self.league.items() if p.learnable}
        self._jit_logits = jax.jit(
            lambda p, o: self.net.apply({"params": p}, o))
        self._jit_update = jax.jit(self._update_impl)
        self._snapshots = 0
        # LocalAlgorithm checkpoint surface
        self.params = self.league[MAIN].params
        self.target_params = self.params
        self.opt_state = self._opt_states[MAIN]
        self._init_local_state()

    # -------------------------------------------------------------- play

    def _act(self, params, state, greedy: bool = False
             ) -> Tuple[int, np.ndarray]:
        g = self.game
        obs = g.observation(state)
        logits, _ = self._jit_logits(params, jnp.asarray(obs)[None])
        logits = np.asarray(logits[0], np.float64)
        legal = g.legal_actions(state)
        mask = np.full_like(logits, -np.inf)
        mask[legal] = 0.0
        logits = logits + mask
        if greedy:
            return int(np.argmax(logits)), obs
        z = logits - logits.max()
        p = np.exp(z) / np.exp(z).sum()
        return int(self._np_rng.choice(len(p), p=p)), obs

    def _play_game(self, pa, pb) -> Tuple[float, List, List]:
        """One game, player a moves first. Returns (outcome for a in
        {1, 0.5, 0}, a's trajectory, b's trajectory) where each
        trajectory is [(obs, action)]."""
        g = self.game
        state = g.initial_state()
        trajs = ([], [])
        params = (pa, pb)
        mover = 0
        while True:
            tv = g.terminal_value(state)
            if tv is not None:
                # tv is from the perspective of the player TO MOVE
                # (-1 = previous mover won, 0 = draw)
                if tv == 0.0:
                    return 0.5, trajs[0], trajs[1]
                winner = 1 - mover  # previous mover
                return (1.0 if winner == 0 else 0.0,
                        trajs[0], trajs[1])
            a, obs = self._act(params[mover], state)
            trajs[mover].append((obs, a))
            state = g.next_state(state, a)
            mover = 1 - mover

    # ---------------------------------------------------------- learning

    def _update_impl(self, params, opt_state, obs, actions, returns):
        def loss_fn(p):
            logits, values = self.net.apply({"params": p}, obs)
            logp = jax.nn.log_softmax(logits)
            chosen = jnp.take_along_axis(
                logp, actions[:, None], axis=1)[:, 0]
            adv = returns - values
            pg = -jnp.mean(chosen * jax.lax.stop_gradient(adv))
            vf = jnp.mean(adv ** 2)
            ent = -jnp.mean(jnp.sum(jnp.exp(logp) * logp, axis=1))
            cfg = self.config
            return (pg + cfg["vf_coeff"] * vf
                    - cfg["entropy_coeff"] * ent), (pg, vf, ent)

        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        return optax.apply_updates(params, updates), opt_state, loss

    def _learn_from(self, pid: str, traj: List, outcome: float):
        if not traj:
            return
        player = self.league[pid]
        ret = 2.0 * outcome - 1.0  # {0, 0.5, 1} -> {-1, 0, +1}
        obs = jnp.asarray(np.stack([o for o, _ in traj]))
        actions = jnp.asarray(np.array([a for _, a in traj], np.int32))
        returns = jnp.full((len(traj),), ret, jnp.float32)
        player.params, self._opt_states[pid], _ = self._jit_update(
            player.params, self._opt_states[pid], obs, actions, returns)

    # -------------------------------------------------------- matchmaking

    def _win_rate(self, a: str, b: str) -> float:
        return self.payoff.get(a, {}).get(b, 0.5)

    def _record(self, a: str, b: str, outcome_a: float):
        ema = self.config["payoff_ema"]
        for x, y, o in ((a, b, outcome_a), (b, a, 1.0 - outcome_a)):
            cur = self.payoff.setdefault(x, {}).get(y, 0.5)
            self.payoff[x][y] = (1 - ema) * cur + ema * o

    def _choose_opponent(self, pid: str) -> str:
        """Reference league_builder.get_match: mains mix self-play with
        PFSP over the whole league; main exploiters target the current
        main; league exploiters PFSP over everyone."""
        player = self.league[pid]
        others = [q for q in self.league if q != pid]
        if player.ptype == MAIN_EXPLOITER:
            return MAIN
        if player.ptype == MAIN and \
                self._np_rng.random() < self.config["main_self_play_prob"]:
            return MAIN  # self-play
        rates = np.array([self._win_rate(pid, q) for q in others])
        return str(self._np_rng.choice(
            others, p=pfsp_weights(rates)))

    def _snapshot_main(self):
        pid = f"{HISTORICAL}_{self._snapshots}"
        self.league[pid] = LeaguePlayer(pid, HISTORICAL,
                                        self.league[MAIN].params)
        self._snapshots += 1
        hist = [p for p in self.league.values()
                if p.ptype == HISTORICAL]
        if len(hist) > self.config["max_historical"]:
            oldest = min(hist, key=lambda p: int(p.pid.rsplit("_", 1)[-1]))
            del self.league[oldest.pid]
            # the payoff table must not accrete dead opponents
            self.payoff.pop(oldest.pid, None)
            for row in self.payoff.values():
                row.pop(oldest.pid, None)

    # ------------------------------------------------------------- driver

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        learners = [pid for pid, p in self.league.items() if p.learnable]
        games = 0
        for i in range(cfg["matches_per_iter"]):
            pid = learners[i % len(learners)]
            opp = self._choose_opponent(pid)
            first = bool(self._np_rng.integers(2))
            pa, pb = (pid, opp) if first else (opp, pid)
            out_a, ta, tb = self._play_game(self.league[pa].params,
                                            self.league[pb].params)
            out_for_pid = out_a if first else 1.0 - out_a
            traj = ta if first else tb
            self._learn_from(pid, traj, out_for_pid)
            if opp != pid:
                self._record(pid, opp, out_for_pid)
            self.league[pid].games += 1
            games += 1
        self._iteration += 1
        self._timesteps_total += games
        if self._iteration % cfg["snapshot_interval"] == 0:
            self._snapshot_main()
        self.params = self.league[MAIN].params  # checkpoint surface
        self.opt_state = self._opt_states[MAIN]
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "num_env_steps_sampled": self._timesteps_total,
            "episodes_this_iter": games,
            "episode_reward_mean":
                2.0 * self.eval_vs_random(MAIN, 4) - 1.0,
            "league_size": len(self.league),
            "num_historical": sum(1 for p in self.league.values()
                                  if p.ptype == HISTORICAL),
            "main_vs_random_win_rate": self.eval_vs_random(MAIN, 20),
            "payoff_main": dict(self.payoff.get(MAIN, {})),
            "time_total_s": time.time() - self._t_start,
        }

    train = step  # Tune surface

    # ---------------------------------------------------------- checkpoint

    def save_checkpoint(self) -> Dict[str, Any]:
        """The league IS the training state: every player's params,
        the payoff matrix, and the snapshot counter resume together
        (reference: the league builder checkpoints its whole roster)."""
        return {
            "league": {pid: {"ptype": p.ptype, "games": p.games,
                             "params": jax.device_get(p.params)}
                       for pid, p in self.league.items()},
            "opt_states": {pid: jax.device_get(s)
                           for pid, s in self._opt_states.items()},
            "payoff": {a: dict(r) for a, r in self.payoff.items()},
            "snapshots": self._snapshots,
            "iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
        }

    def load_checkpoint(self, state: Dict[str, Any]):
        def as_jnp(t):
            return jax.tree_util.tree_map(
                jnp.asarray, t,
                is_leaf=lambda x: isinstance(x, (np.ndarray,
                                                 np.generic)))

        self.league = {
            pid: LeaguePlayer(pid, ent["ptype"], as_jnp(ent["params"]))
            for pid, ent in state["league"].items()}
        for pid, ent in state["league"].items():
            self.league[pid].games = ent.get("games", 0)
        self._opt_states = {pid: as_jnp(s)
                            for pid, s in state["opt_states"].items()}
        self.payoff = {a: dict(r) for a, r in state["payoff"].items()}
        self._snapshots = state["snapshots"]
        self._iteration = state["iteration"]
        self._timesteps_total = state["timesteps_total"]
        self.params = self.league[MAIN].params
        self.target_params = self.params
        self.opt_state = self._opt_states[MAIN]

    # ---------------------------------------------------------- evaluation

    def _random_move(self, state) -> int:
        legal = self.game.legal_actions(state)
        return int(self._np_rng.choice(legal))

    def eval_vs_random(self, pid: str, n_games: int = 20) -> float:
        """Win rate (draws = 0.5) of ``pid`` against a uniform-random
        player, alternating first move."""
        g = self.game
        total = 0.0
        params = self.league[pid].params
        for i in range(n_games):
            state = g.initial_state()
            me_first = i % 2 == 0
            mover_is_me = me_first
            while True:
                tv = g.terminal_value(state)
                if tv is not None:
                    if tv == 0.0:
                        total += 0.5
                    else:
                        # previous mover won
                        total += 0.0 if mover_is_me else 1.0
                    break
                if mover_is_me:
                    a, _ = self._act(params, state, greedy=True)
                else:
                    a = self._random_move(state)
                state = g.next_state(state, a)
                mover_is_me = not mover_is_me
        return total / n_games
