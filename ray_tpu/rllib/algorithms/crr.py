"""CRR — critic-regularized regression for offline RL.

Reference analogue: rllib/algorithms/crr/ (crr.py, torch/crr_torch_policy
.py; Wang et al. 2020): the critic learns by standard TD on the dataset;
the actor is advantage-weighted behavior cloning — log-prob of dataset
actions weighted by f(A(s,a)) where the advantage baseline is the mean Q
over policy samples, and f is ``binary`` (indicator A>0) or ``exp``
(clipped exp(A/beta)). No environment interaction; same SAC net layout
(stochastic squashed-Gaussian actor + twin critics).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithms.sac import (SACConfig, SACPolicy,
                                          _SACNets, _dataset_action_logp,
                                          _squash)
from ray_tpu.rllib.offline import (OfflineAlgorithmMixin,
                                   OfflineDataConfigMixin)
from ray_tpu.rllib.sample_batch import SampleBatch


class CRRPolicy(SACPolicy):
    def _update_impl(self, params, target_params, log_alpha, opt_state,
                     batch, rng):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        beta = cfg.get("temperature", 1.0)
        n_samp = cfg.get("advantage_num_actions", 4)
        weight_type = cfg.get("weight_type", "exp")  # static: py branch ok
        obs = batch[SampleBatch.OBS]
        nobs = batch[SampleBatch.NEXT_OBS]
        acts = batch["raw_actions"]
        rews = batch[SampleBatch.REWARDS]
        not_done = 1.0 - batch[SampleBatch.DONES].astype(jnp.float32)
        rngs = jax.random.split(rng, 3)

        # TD target from the target nets + target policy sample
        mean_n, log_std_n = self.model.apply(
            {"params": target_params}, nobs, method=_SACNets.pi)
        next_a, _ = _squash(mean_n, log_std_n, rngs[0])
        tq1, tq2 = self.model.apply({"params": target_params}, nobs,
                                    next_a, method=_SACNets.q)
        target_q = rews + gamma * not_done * jnp.minimum(tq1, tq2)
        target_q = jax.lax.stop_gradient(target_q)

        def loss_fn(trainables):
            p, _la = trainables
            q1, q2 = self.model.apply({"params": p}, obs, acts,
                                      method=_SACNets.q)
            critic_loss = jnp.mean((q1 - target_q) ** 2
                                   + (q2 - target_q) ** 2)

            # advantage baseline: mean Q over n policy samples at s
            mean, log_std = self.model.apply({"params": p}, obs,
                                             method=_SACNets.pi)
            def one(k):
                a, _ = _squash(mean, log_std, k)
                fq1, fq2 = self.model.apply(
                    {"params": jax.lax.stop_gradient(p)}, obs, a,
                    method=_SACNets.q)
                return jnp.minimum(fq1, fq2)
            v_est = jnp.mean(
                jax.vmap(one)(jax.random.split(rngs[1], n_samp)), axis=0)
            adv = jax.lax.stop_gradient(
                jnp.minimum(q1, q2) - v_est)
            if weight_type == "binary":
                w = (adv > 0).astype(jnp.float32)
            else:
                w = jnp.minimum(jnp.exp(adv / beta),
                                cfg.get("max_weight", 20.0))
            w = jax.lax.stop_gradient(w)

            data_logp = _dataset_action_logp(acts, mean, log_std)
            actor_loss = -jnp.mean(w * data_logp)

            total = critic_loss + actor_loss
            return total, {"critic_loss": critic_loss,
                           "actor_loss": actor_loss,
                           "mean_weight": jnp.mean(w),
                           "mean_advantage": jnp.mean(adv),
                           "mean_q": jnp.mean(q1)}

        (loss_val, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)((params, log_alpha))
        updates, opt_state = self.optimizer.update(
            grads, opt_state, (params, log_alpha))
        params, log_alpha = optax.apply_updates((params, log_alpha),
                                                updates)
        tau = cfg.get("tau", 0.005)
        target_params = jax.tree_util.tree_map(
            lambda t, p: (1 - tau) * t + tau * p, target_params, params)
        stats = dict(stats)
        stats["total_loss"] = loss_val
        return params, target_params, log_alpha, opt_state, stats


class CRRConfig(OfflineDataConfigMixin, SACConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CRR)
        self._config.update({
            "input_path": None,
            "weight_type": "exp",  # or "binary"
            "temperature": 1.0,
            "max_weight": 20.0,
            "advantage_num_actions": 4,
            "train_batch_size": 256,
            "num_iters_per_step": 10,
        })


class CRR(OfflineAlgorithmMixin, Algorithm):
    _policy_cls = CRRPolicy
    _default_config_cls = CRRConfig

    def setup(self, config):
        super().setup(config)
        self._load_offline_dataset()

    def training_step(self) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        cfg = self.config
        bs = cfg["train_batch_size"]
        stats: Dict[str, float] = {}
        for _ in range(cfg.get("num_iters_per_step", 10)):
            stats = policy.learn_on_batch(self._offline_minibatch(bs))
            self._timesteps_total += bs
        self.workers.sync_weights()
        return {"num_env_steps_sampled_this_iter": 0,
                **{f"learner/{k}": v for k, v in stats.items()}}
