"""QMIX — monotonic value-function factorisation for cooperative MARL.

Reference analogue: rllib/algorithms/qmix/ (qmix.py, qmix_policy.py,
model.py QMixer; Rashid et al. 2018): per-agent Q-networks (parameters
shared across agents) whose chosen-action values are mixed into a team
Q_tot by a hypernetwork-generated MONOTONIC mixing net conditioned on
the global state; trained end-to-end by TD on the team reward.

Joint transitions (all agents synchronized + global state) don't fit
the per-policy split that MultiAgentRolloutWorker produces, so — like
the reference, whose QMIX requires grouped agents and samples whole
episodes — this algorithm owns its env loop: an epsilon-greedy joint
collector over a cooperative MultiAgentEnv, a joint replay buffer, and
ONE jitted update for the double-Q mixed TD loss.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm
from ray_tpu.rllib.env import (Discrete, MultiAgentCartPole,
                               MultiAgentEnv, _BUILTIN_ENVS, make_env)
from ray_tpu.rllib.replay_buffers import ReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch


class CooperativeCartPole(MultiAgentCartPole):
    """Team CartPole: episode ends when ANY pole falls; every agent
    receives the TEAM reward (mean of alive rewards) — a minimal fully
    cooperative env for value-decomposition tests (reference analogue:
    the grouped TwoStepGame in rllib/examples/env/two_step_game.py).
    Construction/reset come from MultiAgentCartPole; only the
    cooperative step() differs."""

    def step(self, action_dict: Dict[Any, Any]):
        obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
        any_term, any_trunc, team_r = False, False, 0.0
        for aid, a in action_dict.items():
            o, r, term, trunc, info = self._envs[aid].step(a)
            obs[aid], infos[aid] = o, info
            team_r += float(r)
            any_term |= term
            any_trunc |= trunc
        team_r /= max(1, len(action_dict))
        for aid in action_dict:
            rews[aid] = team_r
            terms[aid] = any_term
            truncs[aid] = any_trunc
        terms["__all__"] = any_term
        truncs["__all__"] = any_trunc
        return obs, rews, terms, truncs, infos


_BUILTIN_ENVS["CoopCartPole"] = CooperativeCartPole


class _AgentQNet(nn.Module):
    """Shared per-agent Q-network."""

    num_actions: int
    hidden: int = 64

    @nn.compact
    def __call__(self, obs):
        x = nn.relu(nn.Dense(self.hidden)(obs))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.num_actions)(x)


class _QMixer(nn.Module):
    """Monotonic mixer: state-conditioned hypernetworks emit
    NON-NEGATIVE (abs) weights so ∂Q_tot/∂Q_i ≥ 0 (reference:
    qmix/model.py QMixer)."""

    n_agents: int
    embed: int = 32

    @nn.compact
    def __call__(self, agent_qs, state):
        # agent_qs: (B, n), state: (B, ds)
        b = agent_qs.shape[0]
        w1 = jnp.abs(nn.Dense(self.n_agents * self.embed,
                              name="hyper_w1")(state))
        w1 = w1.reshape(b, self.n_agents, self.embed)
        b1 = nn.Dense(self.embed, name="hyper_b1")(state)
        hidden = nn.elu(jnp.einsum("bn,bne->be", agent_qs, w1) + b1)
        w2 = jnp.abs(nn.Dense(self.embed, name="hyper_w2")(state))
        b2 = nn.Dense(1, name="hyper_b2_out")(
            nn.relu(nn.Dense(self.embed, name="hyper_b2_h")(state)))
        return jnp.sum(hidden * w2, axis=-1) + b2[..., 0]  # (B,)


class QMixConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or QMix)
        self._config.update({
            "env": "CoopCartPole",
            "lr": 5e-4,
            "mixer_embed": 32,
            "agent_hidden": 64,
            "double_q": True,
            "replay_buffer_capacity": 50_000,
            "learning_starts": 500,
            "train_batch_size": 64,
            "rollout_fragment_length": 64,
            "target_network_update_freq": 400,
            "initial_epsilon": 1.0,
            "final_epsilon": 0.05,
            "epsilon_timesteps": 8_000,
            "training_intensity": 2,
        })


class QMix(LocalAlgorithm):
    _default_config_cls = QMixConfig

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        self.env = make_env(cfg["env"], cfg.get("env_config"))
        if not isinstance(self.env, MultiAgentEnv):
            raise ValueError("QMIX needs a cooperative MultiAgentEnv")
        if not isinstance(self.env.action_space, Discrete):
            raise ValueError("QMIX is discrete-action only")
        self.agent_ids = list(self.env.agent_ids)
        self.n_agents = len(self.agent_ids)
        self.n_actions = self.env.action_space.n
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.state_dim = self.obs_dim * self.n_agents  # concat of obs

        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        self.qnet = _AgentQNet(self.n_actions, cfg["agent_hidden"])
        self.mixer = _QMixer(self.n_agents, cfg["mixer_embed"])
        k1, k2 = jax.random.split(self._next_rng())
        dummy_obs = jnp.zeros((1, self.obs_dim))
        self.params = {
            "agent": self.qnet.init(k1, dummy_obs)["params"],
            "mixer": self.mixer.init(
                k2, jnp.zeros((1, self.n_agents)),
                jnp.zeros((1, self.state_dim)))["params"],
        }
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(10.0), optax.adam(cfg["lr"]))
        self.opt_state = self.optimizer.init(self.params)
        self._jit_q = jax.jit(self._q_impl)
        self._jit_update = jax.jit(self._update_impl)

        self.replay = ReplayBuffer(cfg["replay_buffer_capacity"],
                                   seed=cfg.get("seed"))
        self._init_local_state()
        self._obs, _ = self.env.reset(seed=cfg.get("seed"))
        self._episode_reward = 0.0

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ---- jitted programs ----

    def _q_impl(self, agent_params, obs):
        """obs (B, n, do) -> per-agent Q (B, n, A)."""
        b, n, do = obs.shape
        q = self.qnet.apply({"params": agent_params},
                            obs.reshape(b * n, do))
        return q.reshape(b, n, self.n_actions)

    def _update_impl(self, params, target_params, opt_state, batch):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        obs = batch["obs"]            # (B, n, do)
        nobs = batch["next_obs"]
        acts = batch["actions"].astype(jnp.int32)  # (B, n)
        rews = batch["rewards"]       # (B,) team
        not_done = 1.0 - batch["dones"].astype(jnp.float32)
        b = obs.shape[0]
        state = obs.reshape(b, -1)
        next_state = nobs.reshape(b, -1)

        # target: per-agent best next Q (double-Q uses online argmax)
        tq_next = self._q_impl(target_params["agent"], nobs)
        if cfg.get("double_q", True):
            oq_next = self._q_impl(params["agent"], nobs)
            best = jnp.argmax(oq_next, axis=-1)
        else:
            best = jnp.argmax(tq_next, axis=-1)
        q_next = jnp.take_along_axis(tq_next, best[..., None],
                                     axis=-1)[..., 0]  # (B, n)
        qtot_next = self.mixer.apply({"params": target_params["mixer"]},
                                     q_next, next_state)
        y = jax.lax.stop_gradient(
            rews + gamma * not_done * qtot_next)

        def loss_fn(p):
            q = self._q_impl(p["agent"], obs)
            q_sel = jnp.take_along_axis(q, acts[..., None],
                                        axis=-1)[..., 0]  # (B, n)
            qtot = self.mixer.apply({"params": p["mixer"]}, q_sel, state)
            td = qtot - y
            return jnp.mean(td ** 2), {
                "mean_qtot": jnp.mean(qtot),
                "mean_td_error": jnp.mean(jnp.abs(td))}

        (loss_val, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        stats = dict(stats)
        stats["loss"] = loss_val
        return params, opt_state, stats

    # ---- acting / collection ----

    def _joint_actions(self, obs_dict, epsilon: float):
        obs = np.stack([obs_dict[a] for a in self.agent_ids])[None]
        q = np.asarray(self._jit_q(self.params["agent"],
                                   jnp.asarray(obs)))[0]  # (n, A)
        greedy = np.argmax(q, axis=-1)
        rand = self._np_rng.integers(self.n_actions, size=self.n_agents)
        pick = self._np_rng.random(self.n_agents) < epsilon
        acts = np.where(pick, rand, greedy)
        return {a: int(acts[i]) for i, a in enumerate(self.agent_ids)}

    def _collect(self, num_steps: int, epsilon: float) -> int:
        def act(obs_dict):
            acts = self._joint_actions(obs_dict, epsilon)
            stored = np.array([acts[a] for a in self.agent_ids],
                              np.int64)
            return acts, stored
        return self._collect_joint(act, num_steps)

    # ---- Trainable / Algorithm surface ----

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        n = self._collect(cfg["rollout_fragment_length"], eps)
        self._timesteps_total += n
        stats: Dict[str, float] = {}
        if len(self.replay) >= cfg["learning_starts"]:
            for _ in range(max(1, cfg.get("training_intensity", 1))):
                train = self.replay.sample(cfg["train_batch_size"])
                jbatch = {k: jnp.asarray(v) for k, v in train.items()
                          if isinstance(v, np.ndarray)
                          and v.dtype != object}
                self.params, self.opt_state, jstats = self._jit_update(
                    self.params, self.target_params, self.opt_state,
                    jbatch)
                stats = {k: float(v) for k, v in jstats.items()}
            self._maybe_sync_target(n)
        return {
            "num_env_steps_sampled_this_iter": n,
            "epsilon": eps,
            "replay_size": len(self.replay),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        out = self._eval_episodes(
            lambda obs: self._joint_actions(obs, epsilon=0.0),
            num_episodes)
        # restore the training env stream; the interrupted episode's
        # partial reward must not leak into the next episode's metric
        self._obs, _ = self.env.reset()
        self._episode_reward = 0.0
        return out

    def compute_joint_actions(self, obs_dict):
        """Greedy joint action for deployment."""
        return self._joint_actions(obs_dict, epsilon=0.0)

