"""A3C — asynchronous advantage actor-critic.

Reference analogue: rllib/algorithms/a3c/a3c.py (training_step: async
grad requests — rollout workers compute gradients on their own samples
and the learner applies them HogWild-style as they arrive, pushing fresh
weights back to just the contributing worker; no global barrier).

Same decomposition here: ``JaxPolicy.compute_gradients`` runs the jitted
loss+grad worker-side, the grad pytree ships through the object store,
and the driver applies it with ``apply_gradients`` (same optax chain as
``learn_on_batch``, so grad clipping still applies).
"""

from __future__ import annotations

from typing import Any, Dict

import ray_tpu
from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.algorithms.pg import A2CConfig, A2CPolicy


def _sample_and_grad(worker):
    """Runs inside a rollout worker via ``worker.apply``."""
    batch = worker.sample()
    grads, stats = worker.policy.compute_gradients(batch)
    return grads, stats, batch.count


class A3CConfig(A2CConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A3C)
        self._config.update({
            "num_workers": 2,
            "lr": 1e-3,
            "rollout_fragment_length": 50,
            "train_batch_size": 500,  # unused: updates are per-fragment
            # grad applications per training_step before reporting
            "max_grads_per_step": 8,
        })


class A3C(Algorithm):
    _policy_cls = A2CPolicy
    _default_config_cls = A3CConfig

    def setup(self, config):
        super().setup(config)
        if not self.workers.remote_workers:
            raise ValueError("A3C requires num_workers >= 1 "
                             "(use A2C for the synchronous variant)")
        self._grad_futs: Dict[Any, Any] = {}
        for w in self.workers.remote_workers:
            self._launch(w)

    def _launch(self, worker):
        self._grad_futs[worker.apply.remote(_sample_and_grad)] = worker

    def training_step(self) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        stats: Dict[str, float] = {}
        sampled = 0
        applied = 0
        budget = self.config.get("max_grads_per_step", 8)
        while applied < budget:
            # block for the first grad, then drain whatever else is ready
            timeout = 60.0 if applied == 0 else 0.0
            ready, _ = ray_tpu.wait(list(self._grad_futs),
                                    num_returns=1, timeout=timeout)
            if not ready:
                break
            fut = ready[0]
            worker = self._grad_futs.pop(fut)
            grads, stats, count = ray_tpu.get(fut)
            policy.apply_gradients(grads)
            sampled += count
            applied += 1
            # fresh weights to JUST this worker (the async part: other
            # workers keep sampling with slightly stale policies)
            worker.set_weights.remote(ray_tpu.put(policy.get_weights()))
            self._launch(worker)
        self._timesteps_total += sampled
        return {
            "num_env_steps_sampled_this_iter": sampled,
            "num_grads_applied": applied,
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def cleanup(self):
        self._grad_futs.clear()
        super().cleanup()
