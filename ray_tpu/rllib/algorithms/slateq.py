"""SlateQ — slate recommendation Q-learning with per-item decomposition.

Reference analogue: rllib/algorithms/slateq/ (slateq.py,
slateq_tf_policy.py; Ie et al. 2019 "SlateQ: A Tractable Decomposition
for Reinforcement Learning with Recommendation Sets"): the slate
Q-value decomposes over items via the user's conditional choice model,

    Q(s, A) = sum_{i in A} P(click i | s, A) * q(s, i),

so only per-item q-values are learned (SARSA on the clicked item) and
slate optimization reduces to a top-k ranking — no combinatorial action
space. The environment is a RecSim-style interest-evolution simulator
(reference: recsim wrappers in rllib/examples/env/recommender_system*).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm
from ray_tpu.rllib.replay_buffers import ReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch


class InterestEvolutionEnv:
    """RecSim-class user simulator (reference analogue:
    recsim interest_evolution): ``num_docs`` candidate documents with
    fixed topic vectors; the user's interest vector drifts toward
    clicked topics; the conditional choice model is multinomial-logit
    over the slate plus a no-click option. Observation = user interest
    (the doc corpus is static and known to the agent via the env)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        cfg = config or {}
        self.num_docs = int(cfg.get("num_docs", 20))
        self.slate_size = int(cfg.get("slate_size", 3))
        self.num_topics = int(cfg.get("num_topics", 6))
        self.horizon = int(cfg.get("horizon", 20))
        self.no_click_mass = float(cfg.get("no_click_mass", 1.0))
        self.interest_step = float(cfg.get("interest_step", 0.2))
        rng = np.random.default_rng(cfg.get("doc_seed", 0))
        # fixed corpus: unit topic vectors + scalar quality (engagement)
        t = rng.normal(size=(self.num_docs, self.num_topics))
        self.doc_topics = (t / np.linalg.norm(t, axis=1, keepdims=True)
                           ).astype(np.float32)
        self.doc_quality = rng.uniform(
            0.2, 1.0, self.num_docs).astype(np.float32)
        self._rng = np.random.default_rng()
        self._interest: Optional[np.ndarray] = None
        self._t = 0

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        v = self._rng.normal(size=self.num_topics)
        self._interest = (v / np.linalg.norm(v)).astype(np.float32)
        self._t = 0
        return self._interest.copy(), {}

    def choice_scores(self, interest: np.ndarray) -> np.ndarray:
        """MNL attractiveness v(s, i) for every doc (the user model —
        SlateQ assumes the choice model is known or separately
        estimated, Ie et al. §4)."""
        return np.exp(self.doc_topics @ interest)

    def step(self, slate) -> Tuple[np.ndarray, float, bool, bool, dict]:
        slate = np.asarray(slate, np.int64)
        scores = self.choice_scores(self._interest)[slate]
        probs = np.concatenate([scores, [self.no_click_mass]])
        probs = probs / probs.sum()
        pick = self._rng.choice(len(slate) + 1, p=probs)
        reward, clicked = 0.0, -1
        if pick < len(slate):
            clicked = int(slate[pick])
            reward = float(self.doc_quality[clicked])
            # interest drifts toward the clicked topic
            ni = (1 - self.interest_step) * self._interest + \
                self.interest_step * self.doc_topics[clicked]
            self._interest = (ni / np.linalg.norm(ni)).astype(np.float32)
        self._t += 1
        return (self._interest.copy(), reward, False,
                self._t >= self.horizon, {"clicked": clicked})


class _ItemQNet(nn.Module):
    """q(s, i) for all docs at once: interest -> (num_docs,) values."""
    num_docs: int
    hidden: int = 64

    @nn.compact
    def __call__(self, interest):
        x = nn.relu(nn.Dense(self.hidden)(interest))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.num_docs)(x)


class SlateQConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SlateQ)
        self._config.update({
            "env": "interest_evolution",
            "env_config": {},
            "lr": 1e-3,
            "gamma": 0.95,
            "rollout_fragment_length": 200,
            "train_batch_size": 128,
            "learning_starts": 500,
            "replay_buffer_capacity": 50_000,
            "target_network_update_freq": 500,
            "initial_epsilon": 1.0,
            "final_epsilon": 0.05,
            "epsilon_timesteps": 4_000,
            "training_intensity": 4,
            "hidden": 64,
        })


class SlateQ(LocalAlgorithm):
    """SlateQ with SARSA-on-clicked-item updates (reference:
    slateq.py; the decomposed target is
    r + gamma * sum_j P(click j | s', A') q(s', j))."""

    _default_config_cls = SlateQConfig

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        env_cfg = cfg.get("env_config") or {}
        if cfg["env"] != "interest_evolution":
            raise ValueError("SlateQ ships the interest_evolution sim")
        self.env = InterestEvolutionEnv(env_cfg)
        self.k = self.env.slate_size
        self.num_docs = self.env.num_docs

        self.qnet = _ItemQNet(self.num_docs, cfg["hidden"])
        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        dummy = jnp.zeros((1, self.env.num_topics))
        self.params = self.qnet.init(self._rng, dummy)["params"]
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        self.optimizer = optax.adam(cfg["lr"])
        self.opt_state = self.optimizer.init(self.params)
        self._jit_q = jax.jit(
            lambda p, o: self.qnet.apply({"params": p}, o))
        self._jit_update = jax.jit(self._update_impl)
        self.replay = ReplayBuffer(cfg["replay_buffer_capacity"],
                                   seed=cfg.get("seed"))
        self._init_local_state()
        self._obs, _ = self.env.reset(seed=cfg.get("seed"))
        self._episode_reward = 0.0

    # ---- slate construction ----

    def _build_slate(self, q_vals: np.ndarray,
                     interest: np.ndarray) -> np.ndarray:
        """Optimal slate under MNL: for top-k selection it suffices to
        rank items by v(s,i) * q(s,i) (Ie et al. Prop. 2 — the
        optimal slate is the top-k of the attractiveness-weighted
        q-values when the null mass is fixed)."""
        v = self.env.choice_scores(interest)
        return np.argsort(-(v * np.maximum(q_vals, 0.0)))[:self.k]

    def _act(self, interest: np.ndarray, epsilon: float) -> np.ndarray:
        if self._np_rng.random() < epsilon:
            return self._np_rng.choice(self.num_docs, self.k,
                                       replace=False)
        q = np.asarray(self._jit_q(self.params,
                                   jnp.asarray(interest[None])))[0]
        return self._build_slate(q, interest)

    # ---- jitted update ----

    def _update_impl(self, params, target_params, opt_state, batch):
        gamma = self.config["gamma"]
        obs, nobs = batch["obs"], batch["next_obs"]
        clicked = batch["clicked"]          # (B,) int; -1 = no click
        reward = batch["rewards"]
        dones = batch["dones"].astype(jnp.float32)
        next_slate = batch["next_slate"]    # (B, k) the NEXT slate (SARSA)
        next_scores = batch["next_scores"]  # (B, k) MNL v(s', j)

        q_next = self.qnet.apply({"params": target_params}, nobs)
        q_sel = jnp.take_along_axis(q_next, next_slate, axis=1)
        # P(click j | s', A') over the next slate + null mass
        null = jnp.full((q_sel.shape[0], 1), self.env.no_click_mass)
        probs = jnp.concatenate([next_scores, null], axis=1)
        probs = probs / probs.sum(axis=1, keepdims=True)
        v_next = jnp.sum(probs[:, :-1] * q_sel, axis=1)
        target = reward + gamma * (1.0 - dones) * v_next
        target = jax.lax.stop_gradient(target)

        has_click = (clicked >= 0).astype(jnp.float32)
        safe_idx = jnp.maximum(clicked, 0)

        def loss_fn(p):
            q = self.qnet.apply({"params": p}, obs)
            q_clicked = jnp.take_along_axis(
                q, safe_idx[:, None], axis=1)[:, 0]
            # only clicked transitions update item q-values (SlateQ's
            # SARSA decomposition learns item-level LTV from clicks)
            err = (q_clicked - target) * has_click
            denom = jnp.maximum(has_click.sum(), 1.0)
            return jnp.sum(err ** 2) / denom, q_clicked

        (loss, q_clicked), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        return (optax.apply_updates(params, updates), opt_state,
                {"loss": loss, "mean_q_clicked": jnp.mean(q_clicked),
                 "click_fraction": jnp.mean(has_click)})

    # ---- env loop ----

    def _collect(self, num_steps: int, epsilon: float) -> int:
        rows: Dict[str, list] = {k: [] for k in (
            "obs", "next_obs", "clicked", "rewards", "dones",
            "next_slate", "next_scores")}
        for _ in range(num_steps):
            slate = self._act(self._obs, epsilon)
            nobs, r, term, trunc, info = self.env.step(slate)
            done = term or trunc
            # SARSA: the NEXT slate under the current policy at s'
            nslate = self._act(nobs, epsilon)
            nscores = self.env.choice_scores(nobs)[nslate]
            rows["obs"].append(self._obs)
            rows["next_obs"].append(nobs)
            rows["clicked"].append(np.int32(info["clicked"]))
            rows["rewards"].append(np.float32(r))
            rows["dones"].append(term)  # horizon truncation bootstraps
            rows["next_slate"].append(nslate.astype(np.int32))
            rows["next_scores"].append(nscores.astype(np.float32))
            self._episode_reward += r
            if done:
                self._episode_reward_window.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nobs
        self.replay.add(SampleBatch(
            {k: np.stack(v) for k, v in rows.items()}))
        return num_steps

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        n = self._collect(cfg["rollout_fragment_length"], eps)
        self._timesteps_total += n
        stats: Dict[str, float] = {}
        if len(self.replay) >= cfg["learning_starts"]:
            for _ in range(max(1, cfg.get("training_intensity", 1))):
                train = self.replay.sample(cfg["train_batch_size"])
                jbatch = {k: jnp.asarray(v) for k, v in train.items()
                          if isinstance(v, np.ndarray)
                          and v.dtype != object}
                self.params, self.opt_state, jstats = self._jit_update(
                    self.params, self.target_params, self.opt_state,
                    jbatch)
                stats = {k: float(v) for k, v in jstats.items()}
            self._maybe_sync_target(n)
        return {
            "num_env_steps_sampled_this_iter": n,
            "epsilon": eps,
            "replay_size": len(self.replay),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        out = self._eval_episodes(
            lambda obs: self._act(obs, epsilon=0.0), num_episodes)
        self._obs, _ = self.env.reset()
        self._episode_reward = 0.0
        return out

    def random_baseline(self, num_episodes: int = 20,
                        seed: int = 123) -> float:
        """Mean episode engagement of uniformly random slates."""
        rng = np.random.default_rng(seed)
        totals = []
        for ep in range(num_episodes):
            self.env.reset(seed=seed + ep)
            total = 0.0
            for _ in range(self.env.horizon):
                slate = rng.choice(self.num_docs, self.k, replace=False)
                _, r, _, trunc, _ = self.env.step(slate)
                total += r
                if trunc:
                    break
            totals.append(total)
        return float(np.mean(totals))
