"""DT — Decision Transformer (Chen et al. 2021), offline RL as
sequence modeling.

Reference analogue: rllib/algorithms/dt/ (dt.py, dt_torch_model.py,
segmentation_buffer.py): trajectories become token sequences
[R̂_1, s_1, a_1, R̂_2, s_2, a_2, ...] (R̂ = return-to-go); a small
causal transformer is trained to predict a_t from the prefix ending at
s_t; acting conditions on a target return and feeds back observed
rewards. Trained purely from a JsonReader dataset.

TPU-first: the interleaved (B, 3K, D) token batch runs through jitted
causal attention blocks — pure MXU matmuls with a static mask; the
per-step eval context is a fixed-size rolling window so the acting
forward is ONE compiled program too.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

import logging

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm
from ray_tpu.rllib.env import Discrete, make_env
from ray_tpu.rllib.offline import JsonReader, OfflineDataConfigMixin
from ray_tpu.rllib.sample_batch import SampleBatch

logger = logging.getLogger(__name__)


class _CausalBlock(nn.Module):
    dim: int
    heads: int

    @nn.compact
    def __call__(self, x, mask):
        h = nn.LayerNorm()(x)
        h = nn.SelfAttention(num_heads=self.heads,
                             qkv_features=self.dim,
                             deterministic=True)(h, mask=mask)
        x = x + h
        h = nn.LayerNorm()(x)
        h = nn.Dense(4 * self.dim)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim)(h)
        return x + h


class _DTNet(nn.Module):
    """Interleaved (rtg, state, action) token transformer; action
    logits are read at the STATE token positions (reference:
    dt_torch_model.py)."""

    obs_dim: int
    n_actions: int
    context: int  # K timesteps -> 3K tokens
    dim: int = 64
    heads: int = 4
    layers: int = 2
    max_timestep: int = 1024

    @nn.compact
    def __call__(self, rtg, obs, acts, timesteps):
        # rtg (B,K,1), obs (B,K,do), acts (B,K) int, timesteps (B,K) int
        b, k = acts.shape
        t_emb = nn.Embed(self.max_timestep, self.dim)(
            jnp.clip(timesteps, 0, self.max_timestep - 1))
        r_tok = nn.Dense(self.dim)(rtg) + t_emb
        s_tok = nn.Dense(self.dim)(obs) + t_emb
        a_tok = nn.Embed(self.n_actions + 1, self.dim)(
            jnp.clip(acts + 1, 0, self.n_actions)) + t_emb
        # interleave -> (B, 3K, D): [r_1, s_1, a_1, r_2, ...]
        x = jnp.stack([r_tok, s_tok, a_tok],
                      axis=2).reshape(b, 3 * k, self.dim)
        causal = nn.make_causal_mask(jnp.ones((b, 3 * k)))
        for _ in range(self.layers):
            x = _CausalBlock(self.dim, self.heads)(x, causal)
        x = nn.LayerNorm()(x)
        s_positions = x.reshape(b, k, 3, self.dim)[:, :, 1]  # state toks
        return nn.Dense(self.n_actions)(s_positions)  # (B, K, A)


class DTConfig(OfflineDataConfigMixin, AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DT)
        self._config.update({
            "input_path": None,
            "context_length": 8,
            "embed_dim": 64,
            "num_heads": 4,
            "num_layers": 2,
            "lr": 1e-3,
            "train_batch_size": 64,
            "num_iters_per_step": 20,
            # acting: return prompt (None = best dataset return)
            "target_return": None,
        })


class DT(LocalAlgorithm):
    _default_config_cls = DTConfig

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        self.env = make_env(cfg["env"], cfg.get("env_config"))
        if not isinstance(self.env.action_space, Discrete):
            raise ValueError("this DT implementation is discrete-only")
        self.n_actions = self.env.action_space.n
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.K = cfg["context_length"]

        path = cfg.get("input_path")
        if not path:
            raise ValueError("DT needs config['input_path']")
        self._segment(JsonReader(path).read_all())
        self.target_return = (cfg["target_return"]
                              if cfg["target_return"] is not None
                              else self._best_return)

        self.net = _DTNet(self.obs_dim, self.n_actions, self.K,
                          cfg["embed_dim"], cfg["num_heads"],
                          cfg["num_layers"])
        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        dummy = (jnp.zeros((1, self.K, 1)),
                 jnp.zeros((1, self.K, self.obs_dim)),
                 jnp.zeros((1, self.K), jnp.int32),
                 jnp.zeros((1, self.K), jnp.int32))
        self.params = self.net.init(self._next_rng(), *dummy)["params"]
        self.target_params = {}  # none: not a TD method
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(1.0), optax.adamw(cfg["lr"]))
        self.opt_state = self.optimizer.init(self.params)
        self._jit_update = jax.jit(self._update_impl)
        self._jit_logits = jax.jit(
            lambda p, r, o, a, t: self.net.apply({"params": p},
                                                 r, o, a, t))
        self._init_local_state()

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ---- data ----

    def _segment(self, data: SampleBatch) -> List[Dict[str, np.ndarray]]:
        """Split the flat batch into episodes with returns-to-go
        (reference: dt/segmentation_buffer.py)."""
        obs = np.asarray(data[SampleBatch.OBS], np.float32)
        acts = np.asarray(data[SampleBatch.ACTIONS], np.int64).reshape(-1)
        rews = np.asarray(data[SampleBatch.REWARDS], np.float32)
        dones = np.asarray(data[SampleBatch.DONES], bool)
        eps, start = [], 0
        for t in range(len(rews)):
            if dones[t]:
                sl = slice(start, t + 1)
                r = rews[sl]
                rtg = np.cumsum(r[::-1])[::-1].astype(np.float32)
                eps.append({"obs": obs[sl], "acts": acts[sl],
                            "rtg": rtg,
                            "t": np.arange(t + 1 - start, dtype=np.int64)})
                start = t + 1
        # a trailing fragment (recording stopped mid-episode) has an
        # understated return-to-go — drop it rather than train on it
        if start < len(rews):
            logger.warning(
                "DT: dropping %d-step trailing partial episode "
                "(dataset ends without done=True)", len(rews) - start)
        eps = [e for e in eps if len(e["acts"]) >= 2]
        if not eps:
            raise ValueError(
                "DT: dataset has no usable episodes (need >= 2 steps "
                "ending in done=True)")
        # left-pad each episode with K-1 rows and concatenate into one
        # flat array per field: every context window is then a uniform
        # slice and batch assembly is ONE fancy gather per field, no
        # per-row Python loop
        K = self.K

        def flat(field, pad_val, dtype):
            pads = []
            for e in eps:
                col = e[field]
                pad_shape = (K - 1, *col.shape[1:])
                pads.append(np.full(pad_shape, pad_val, dtype))
                pads.append(col.astype(dtype))
            return np.concatenate(pads)

        self._flat = {
            "obs": flat("obs", 0.0, np.float32),
            "acts": flat("acts", -1, np.int64),
            "rtg": flat("rtg", 0.0, np.float32),
            "t": flat("t", 0, np.int64),
        }
        lengths = np.array([len(e["acts"]) for e in eps], np.int64)
        padded = lengths + (K - 1)
        self._ep_bases = np.concatenate(
            [[0], np.cumsum(padded)[:-1]]).astype(np.int64)
        self._ep_lengths = lengths
        self._best_return = max(float(e["rtg"][0]) for e in eps)
        # the flat arrays are the training store; the per-episode
        # copies would double resident memory — drop them

    def _sample_batch(self, bs: int) -> Dict[str, jnp.ndarray]:
        """One fancy-indexed gather per field from the pre-padded
        episodes (the window ending at step `end-1` is the uniform
        padded slice [end-1, end-1+K))."""
        K = self.K
        ep_ids = self._np_rng.integers(len(self._ep_lengths), size=bs)
        ends = self._np_rng.integers(1, self._ep_lengths[ep_ids] + 1)
        local = (ends[:, None] - 1) + np.arange(K)[None]  # padded coords
        idx = self._ep_bases[ep_ids][:, None] + local     # (bs, K)
        mask = (local >= K - 1).astype(np.float32)
        return {
            "rtg": jnp.asarray(self._flat["rtg"][idx][..., None]),
            "obs": jnp.asarray(self._flat["obs"][idx]),
            "acts": jnp.asarray(self._flat["acts"][idx]),
            "ts": jnp.asarray(self._flat["t"][idx]),
            "mask": jnp.asarray(mask),
        }

    # ---- training ----

    def _update_impl(self, params, opt_state, batch):
        def loss_fn(p):
            logits = self.net.apply({"params": p}, batch["rtg"],
                                    batch["obs"], batch["acts"],
                                    batch["ts"])
            # predict a_t from prefix ending at s_t: the action input
            # at position t is masked out by construction (the token
            # order puts a_t AFTER s_t, and attention is causal)
            targets = jnp.clip(batch["acts"], 0, self.n_actions - 1)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(
                logp, targets[..., None], axis=-1)[..., 0]
            m = batch["mask"]
            loss = jnp.sum(nll * m) / jnp.maximum(m.sum(), 1.0)
            acc = jnp.sum(
                (jnp.argmax(logits, -1) == targets) * m
            ) / jnp.maximum(m.sum(), 1.0)
            return loss, {"action_nll": loss, "action_acc": acc}

        (loss_val, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        stats = dict(stats)
        stats["loss"] = loss_val
        return params, opt_state, stats

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        stats: Dict[str, float] = {}
        for _ in range(cfg.get("num_iters_per_step", 20)):
            self.params, self.opt_state, jstats = self._jit_update(
                self.params, self.opt_state,
                self._sample_batch(cfg["train_batch_size"]))
            stats = {k: float(v) for k, v in jstats.items()}
            self._timesteps_total += cfg["train_batch_size"]
        return {"num_env_steps_sampled_this_iter": 0,
                "target_return": self.target_return,
                **{f"learner/{k}": v for k, v in stats.items()}}

    # ---- acting ----

    def evaluate(self, num_episodes: int = 5,
                 target_return: Optional[float] = None) -> Dict[str, Any]:
        """Autoregressive rollouts conditioned on the target return
        (reference: dt.py evaluate with the rolling context)."""
        K = self.K
        tgt = (target_return if target_return is not None
               else self.target_return)
        rewards = []
        for ep in range(num_episodes):
            o, _ = self.env.reset(seed=20_000 + ep)
            rtg = np.zeros((1, K, 1), np.float32)
            obs = np.zeros((1, K, self.obs_dim), np.float32)
            acts = np.full((1, K), -1, np.int64)
            ts = np.zeros((1, K), np.int64)
            remaining = float(tgt)
            total, done, t = 0.0, False, 0
            while not done:
                # roll the window left; write the current step at K-1
                rtg[0, :-1] = rtg[0, 1:]
                obs[0, :-1] = obs[0, 1:]
                acts[0, :-1] = acts[0, 1:]
                ts[0, :-1] = ts[0, 1:]
                rtg[0, -1, 0] = remaining
                obs[0, -1] = np.asarray(o, np.float32)
                acts[0, -1] = -1  # current action unknown
                ts[0, -1] = min(t, self.net.max_timestep - 1)
                logits = np.asarray(self._jit_logits(
                    self.params, jnp.asarray(rtg), jnp.asarray(obs),
                    jnp.asarray(acts), jnp.asarray(ts)))[0, -1]
                a = int(np.argmax(logits))
                acts[0, -1] = a
                o, r, term, trunc, _ = self.env.step(a)
                total += float(r)
                remaining -= float(r)
                done = term or trunc
                t += 1
            rewards.append(total)
        return {"evaluation": {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_reward_min": float(np.min(rewards)),
            "episode_reward_max": float(np.max(rewards)),
        }}
