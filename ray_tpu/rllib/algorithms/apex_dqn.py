"""Ape-X DQN — distributed prioritized experience replay.

Reference analogue: rllib/algorithms/apex_dqn/apex_dqn.py (Horgan et al.):
many rollout workers with per-worker exploration epsilons feed a replay
ACTOR (not a driver-local buffer); the learner pulls prefetched training
batches from it asynchronously and pushes priority updates back. Here the
replay shard is a ray_tpu actor, sampling futures are kept in flight for
both rollout workers and replay sampling, and the per-worker epsilon
ladder follows the paper: eps_i = base^(1 + i/(N-1) * 7).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.replay_buffers import PrioritizedReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch


class _ReplayShard:
    """Actor wrapping a PrioritizedReplayBuffer (reference:
    utils/actors.py create_colocated replay actors)."""

    def __init__(self, capacity: int, alpha: float, seed=None):
        self._buf = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                            seed=seed)

    def add(self, batch: SampleBatch) -> int:
        self._buf.add(batch)
        return len(self._buf)

    def sample(self, n: int, beta: float) -> SampleBatch:
        if len(self._buf) < n:
            return SampleBatch({})
        return self._buf.sample(n, beta=beta)

    def update_priorities(self, idx, priorities) -> bool:
        self._buf.update_priorities(idx, priorities)
        return True

    def size(self) -> int:
        return len(self._buf)


ReplayShard = ray_tpu.remote(_ReplayShard)


class ApexLoopMixin:
    """The Ape-X orchestration, shared by ApexDQN and ApexDDPG
    (reference: apex_dqn.py and apex_ddpg.py share ApexDQN.training_step
    the same way). Subclasses provide ``_worker_exploration(i, n)`` —
    the per-worker exploration ladder — and a policy whose learn stats
    include per-sample ``td_errors``."""

    def _worker_exploration(self, i: int, n: int) -> Dict[str, Any]:
        raise NotImplementedError

    def _apex_setup(self):
        cfg = self.config
        if not self.workers.remote_workers:
            raise ValueError(
                f"{type(self).__name__} requires num_workers >= 1")
        self.replay_actor = ReplayShard.remote(
            cfg["replay_buffer_capacity"],
            cfg["prioritized_replay_alpha"], cfg.get("seed"))
        # fixed per-worker exploration ladder (no annealing — the ladder
        # IS the exploration schedule in Ape-X)
        n = len(self.workers.remote_workers)
        for i, w in enumerate(self.workers.remote_workers):
            w.set_exploration.remote(**self._worker_exploration(i, n))
        self._sample_futs: Dict[Any, Any] = {}  # sample fut -> worker
        self._replay_futs: list = []  # prefetched train-batch futures
        self._replay_size = 0
        self._steps_since_target_sync = 0
        self._learn_count = 0
        # the ReplayShard actor replaces the driver-local buffer the
        # DQN/DDPG base setup allocated — drop the dead state
        self.replay = None

    def _launch_sample(self, worker):
        fut = worker.sample.remote()
        self._sample_futs[fut] = worker

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        policy = self.workers.local_worker.policy
        stats: Dict[str, float] = {}
        sampled = 0

        for w in self.workers.remote_workers:
            if w not in self._sample_futs.values():
                self._launch_sample(w)

        # 1) drain ready rollout batches into the replay actor
        reaped = 0
        add_futs = []
        while reaped < cfg.get("max_sample_batches_per_iter", 8):
            ready, _ = ray_tpu.wait(list(self._sample_futs),
                                    num_returns=1, timeout=30.0)
            if not ready:
                break
            fut = ready[0]
            worker = self._sample_futs.pop(fut)
            batch = ray_tpu.get(fut)
            sampled += batch.count
            # non-blocking adds; ALL are collected after the drain loop
            # (one blocking round per step, and an add failure still
            # surfaces instead of being dropped unawaited)
            add_futs.append(self.replay_actor.add.remote(batch))
            worker.set_weights.remote(ray_tpu.put(policy.get_weights()))
            self._launch_sample(worker)
            reaped += 1
        if add_futs:
            self._replay_size = ray_tpu.get(add_futs)[-1]
        self._timesteps_total += sampled

        # 2) learner: consume prefetched replay samples, refill pipeline
        if self._replay_size >= cfg["learning_starts"]:
            beta = cfg["prioritized_replay_beta"]
            bs = cfg["train_batch_size"]
            want = cfg.get("train_intensity_per_iter", 4)
            while len(self._replay_futs) < cfg.get("replay_prefetch", 2):
                self._replay_futs.append(
                    self.replay_actor.sample.remote(bs, beta))
            for _ in range(want):
                fut = self._replay_futs.pop(0)
                self._replay_futs.append(
                    self.replay_actor.sample.remote(bs, beta))
                train = ray_tpu.get(fut)
                if train.count == 0:
                    continue
                stats = policy.learn_on_batch(train)
                self._learn_count += 1
                self.replay_actor.update_priorities.remote(
                    train["batch_indexes"], stats.pop("td_errors"))
                self._steps_since_target_sync += train.count
                # hard target sync by period (DQN); DDPG/TD3 polyak
                # inside learn_on_batch and have no update_target
                if (hasattr(policy, "update_target")
                        and self._steps_since_target_sync
                        >= cfg["target_network_update_freq"]):
                    policy.update_target()
                    self._steps_since_target_sync = 0
        stats.pop("td_errors", None)
        return {
            "num_env_steps_sampled_this_iter": sampled,
            "replay_size": self._replay_size,
            "num_learner_steps": self._learn_count,
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def cleanup(self):
        self._sample_futs.clear()
        self._replay_futs.clear()
        try:
            ray_tpu.kill(self.replay_actor)
        except Exception:
            pass
        super().cleanup()


class ApexDQNConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDQN)
        self._config.update({
            "num_workers": 2,
            "prioritized_replay": True,
            "epsilon_base": 0.4,  # per-worker ladder: base^(1+7i/(N-1))
            "replay_prefetch": 2,  # sample futures kept in flight
            "train_batch_size": 64,
            "rollout_fragment_length": 16,
            "learning_starts": 500,
            "target_network_update_freq": 1000,
            "max_sample_batches_per_iter": 8,
            "train_intensity_per_iter": 4,
        })


class ApexDQN(ApexLoopMixin, DQN):
    """DQN with a replay actor between samplers and the learner."""

    _default_config_cls = ApexDQNConfig

    def _worker_exploration(self, i, n):
        base = self.config.get("epsilon_base", 0.4)
        return {"exploration_epsilon": base ** (1 + 7 * i / max(1, n - 1))}

    def setup(self, config):
        super().setup(config)
        self._apex_setup()
        self.workers.local_worker.policy.exploration_epsilon = 0.0
