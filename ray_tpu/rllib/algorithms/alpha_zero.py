"""AlphaZero — self-play MCTS + policy/value network.

Reference analogue: rllib/algorithms/alpha_zero/ (alpha_zero.py,
mcts.py, alpha_zero_policy.py; Silver et al. 2017): a PUCT tree search
guided by a policy/value net, self-play games generating (state,
visit-count policy, outcome) targets, and a jitted cross-entropy +
value-MSE update. TPU-first split: the search tree is host-side numpy
(inherently sequential pointer-chasing), while every leaf evaluation is
a BATCHED jitted net call — the MXU sees one [B, obs] inference per
simulation wave, not per node.

Games implement the two-player zero-sum protocol of ``BoardGame``
(reference analogue: the open_spiel env wrappers the reference's
AlphaZero rides on).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm


# --------------------------------------------------------------- board games


class BoardGame:
    """Two-player zero-sum perfect-information game. States are numpy
    arrays; player +1 moves first; values are from the PERSPECTIVE OF
    THE PLAYER TO MOVE."""

    num_actions: int
    obs_shape: Tuple[int, ...]

    def initial_state(self): ...
    def legal_actions(self, state) -> np.ndarray: ...
    def next_state(self, state, action): ...
    def terminal_value(self, state) -> Optional[float]:
        """None if non-terminal, else the value for the player to move
        (-1 lost, 0 draw; +1 cannot occur — the mover faces the result
        of the opponent's winning move)."""
    def observation(self, state) -> np.ndarray:
        """Canonical obs from the mover's perspective."""


class TicTacToe(BoardGame):
    """3x3; state = (board(9) ints in {-1,0,1}, player-to-move)."""

    num_actions = 9
    obs_shape = (18,)
    _LINES = [(0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6)]

    def initial_state(self):
        return (np.zeros(9, np.int8), 1)

    def legal_actions(self, state):
        return np.flatnonzero(state[0] == 0)

    def next_state(self, state, action):
        board, player = state
        nb = board.copy()
        nb[action] = player
        return (nb, -player)

    def terminal_value(self, state):
        board, player = state
        for a, b, c in self._LINES:
            s = board[a] + board[b] + board[c]
            if s == 3 or s == -3:
                # the line belongs to the player who just moved
                return -1.0
        if not (board == 0).any():
            return 0.0
        return None

    def observation(self, state):
        board, player = state
        mine = (board == player).astype(np.float32)
        theirs = (board == -player).astype(np.float32)
        return np.concatenate([mine, theirs])


class Connect4(BoardGame):
    """6x7 connect-four; state = (board(6,7), player)."""

    ROWS, COLS, K = 6, 7, 4
    num_actions = 7
    obs_shape = (2 * 6 * 7,)

    def initial_state(self):
        return (np.zeros((self.ROWS, self.COLS), np.int8), 1)

    def legal_actions(self, state):
        return np.flatnonzero(state[0][0] == 0)

    def next_state(self, state, action):
        board, player = state
        nb = board.copy()
        col = nb[:, action]
        row = np.flatnonzero(col == 0)[-1]  # lowest empty cell
        nb[row, action] = player
        return (nb, -player)

    def terminal_value(self, state):
        board, player = state
        b = board
        for who in (1, -1):
            m = (b == who)
            # horizontal / vertical / two diagonals via shifted ANDs
            if (m[:, :-3] & m[:, 1:-2] & m[:, 2:-1] & m[:, 3:]).any() or \
               (m[:-3] & m[1:-2] & m[2:-1] & m[3:]).any() or \
               (m[:-3, :-3] & m[1:-2, 1:-2] & m[2:-1, 2:-1]
                & m[3:, 3:]).any() or \
               (m[3:, :-3] & m[2:-1, 1:-2] & m[1:-2, 2:-1]
                & m[:-3, 3:]).any():
                return -1.0  # the line belongs to the previous mover
        if not (b == 0).any():
            return 0.0
        return None

    def observation(self, state):
        board, player = state
        mine = (board == player).astype(np.float32).ravel()
        theirs = (board == -player).astype(np.float32).ravel()
        return np.concatenate([mine, theirs])


GAMES = {"tictactoe": TicTacToe, "connect4": Connect4}


# ---------------------------------------------------------------------- MCTS


class _Node:
    __slots__ = ("state", "prior", "children", "n", "w", "legal",
                 "terminal_v")

    def __init__(self, state, prior: float):
        self.state = state
        self.prior = prior
        self.children: Dict[int, "_Node"] = {}
        self.n = 0
        self.w = 0.0
        self.legal: Optional[np.ndarray] = None
        self.terminal_v: Optional[float] = None

    @property
    def q(self) -> float:
        return self.w / self.n if self.n else 0.0


class MCTS:
    """PUCT search (reference: alpha_zero/mcts.py). ``evaluate(obs
    batch) -> (priors, values)`` is the only net touchpoint."""

    def __init__(self, game: BoardGame, evaluate, c_puct: float = 1.5,
                 dirichlet_alpha: float = 0.6,
                 dirichlet_frac: float = 0.25,
                 rng: Optional[np.random.Generator] = None):
        self.game = game
        self.evaluate = evaluate
        self.c_puct = c_puct
        self.alpha = dirichlet_alpha
        self.frac = dirichlet_frac
        self.rng = rng or np.random.default_rng()

    def run(self, state, num_sims: int, add_noise: bool) -> np.ndarray:
        g = self.game
        root = _Node(state, 1.0)
        self._expand(root, add_noise=add_noise)
        for _ in range(num_sims):
            node, path = root, [root]
            # select to a leaf
            while node.children and node.terminal_v is None:
                node = self._select(node)
                path.append(node)
            if node.terminal_v is not None:
                value = node.terminal_v
            else:
                value = self._expand(node, add_noise=False)
            # backup: value is from the leaf mover's perspective; it
            # flips sign at every ply up the path
            for parent in reversed(path):
                parent.n += 1
                parent.w += value
                value = -value
        counts = np.zeros(g.num_actions, np.float32)
        for a, child in root.children.items():
            counts[a] = child.n
        return counts

    def _select(self, node: _Node) -> _Node:
        sqrt_n = float(np.sqrt(node.n + 1))
        best, best_score = None, -np.inf
        for a, child in node.children.items():
            # child.q is from the CHILD mover's perspective — negate
            u = -child.q + self.c_puct * child.prior * sqrt_n / (
                1 + child.n)
            if u > best_score:
                best, best_score = child, u
        return best

    def _expand(self, node: _Node, add_noise: bool) -> float:
        g = self.game
        tv = g.terminal_value(node.state)
        if tv is not None:
            node.terminal_v = tv
            return tv
        legal = g.legal_actions(node.state)
        node.legal = legal
        obs = g.observation(node.state)[None]
        priors, value = self.evaluate(obs)
        priors, value = np.asarray(priors[0]), float(value[0])
        p = np.zeros(g.num_actions, np.float64)
        p[legal] = np.exp(priors[legal] - priors[legal].max())
        p /= p.sum()
        if add_noise:
            noise = self.rng.dirichlet([self.alpha] * len(legal))
            p[legal] = (1 - self.frac) * p[legal] + self.frac * noise
        for a in legal:
            node.children[int(a)] = _Node(
                g.next_state(node.state, int(a)), float(p[a]))
        return value


# ----------------------------------------------------------------- algorithm


class _PVNet(nn.Module):
    num_actions: int
    hidden: int = 128

    @nn.compact
    def __call__(self, obs):
        x = nn.relu(nn.Dense(self.hidden)(obs))
        x = nn.relu(nn.Dense(self.hidden)(x))
        logits = nn.Dense(self.num_actions)(x)
        value = jnp.tanh(nn.Dense(1)(x))[..., 0]
        return logits, value


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or AlphaZero)
        self._config.update({
            "env": "tictactoe",
            "num_sims": 25,
            "c_puct": 1.5,
            "dirichlet_alpha": 0.6,
            "dirichlet_frac": 0.25,
            "temperature_moves": 4,  # sample moves while ply < this
            "games_per_iteration": 24,
            "train_batch_size": 256,
            "sgd_iters": 8,
            "lr": 3e-3,
            "l2_coeff": 1e-4,
            "replay_capacity": 20_000,
            "hidden": 128,
        })


class AlphaZero(LocalAlgorithm):
    """Self-play AlphaZero (reference: alpha_zero.py training_step:
    self-play sample → replay → SGD on CE+MSE)."""

    _default_config_cls = AlphaZeroConfig

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        game_cls = GAMES.get(cfg["env"])
        if game_cls is None:
            raise ValueError(
                f"AlphaZero env must be one of {sorted(GAMES)}")
        self.game = game_cls()
        self.net = _PVNet(self.game.num_actions, cfg["hidden"])
        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        dummy = jnp.zeros((1,) + self.game.obs_shape)
        self.params = self.net.init(self._rng, dummy)["params"]
        self.target_params = self.params  # unused; LocalAlgorithm ckpt
        self.optimizer = optax.adam(cfg["lr"])
        self.opt_state = self.optimizer.init(self.params)
        self._jit_eval = jax.jit(
            lambda p, o: self.net.apply({"params": p}, o))
        self._jit_update = jax.jit(self._update_impl)
        self._replay: List[Tuple[np.ndarray, np.ndarray, float]] = []
        self._init_local_state()

    def _evaluate(self, obs):
        logits, value = self._jit_eval(self.params, jnp.asarray(obs))
        return np.asarray(logits), np.asarray(value)

    def _self_play_game(self) -> Tuple[List, float]:
        g, cfg = self.game, self.config
        mcts = MCTS(g, self._evaluate, cfg["c_puct"],
                    cfg["dirichlet_alpha"], cfg["dirichlet_frac"],
                    rng=self._np_rng)
        state = g.initial_state()
        history = []  # (obs, pi, mover_sign)
        ply = 0
        while True:
            tv = g.terminal_value(state)
            if tv is not None:
                # tv is for the player to move at the terminal state
                outcome_for_mover = tv
                break
            counts = mcts.run(state, cfg["num_sims"], add_noise=True)
            pi = counts / counts.sum()
            history.append((g.observation(state), pi, ply))
            if ply < cfg["temperature_moves"]:
                action = int(self._np_rng.choice(len(pi), p=pi))
            else:
                action = int(np.argmax(pi))
            state = g.next_state(state, action)
            ply += 1
        # assign z to every position from ITS mover's perspective:
        # the terminal mover sees `tv`; signs alternate backwards
        samples = []
        for obs, pi, p_ply in history:
            sign = 1.0 if (ply - p_ply) % 2 == 0 else -1.0
            samples.append((obs, pi, sign * outcome_for_mover))
        return samples, outcome_for_mover

    def _update_impl(self, params, opt_state, obs, pi, z):
        def loss_fn(p):
            logits, value = self.net.apply({"params": p}, obs)
            logp = jax.nn.log_softmax(logits)
            policy_loss = -jnp.mean(jnp.sum(pi * logp, axis=-1))
            value_loss = jnp.mean((value - z) ** 2)
            l2 = sum(jnp.sum(w ** 2) for w in jax.tree_util.tree_leaves(p))
            total = policy_loss + value_loss + \
                self.config["l2_coeff"] * l2
            return total, (policy_loss, value_loss)

        (total, (pl, vl)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        return (optax.apply_updates(params, updates), opt_state,
                {"total_loss": total, "policy_loss": pl,
                 "value_loss": vl})

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n_steps = 0
        for _ in range(cfg["games_per_iteration"]):
            samples, outcome = self._self_play_game()
            self._replay.extend(samples)
            n_steps += len(samples)
            self._episode_reward_window.append(outcome)
        self._replay = self._replay[-cfg["replay_capacity"]:]
        self._timesteps_total += n_steps
        stats: Dict[str, float] = {}
        if self._replay:
            for _ in range(cfg["sgd_iters"]):
                idx = self._np_rng.integers(
                    0, len(self._replay),
                    min(cfg["train_batch_size"], len(self._replay)))
                obs = jnp.asarray(
                    np.stack([self._replay[i][0] for i in idx]))
                pi = jnp.asarray(
                    np.stack([self._replay[i][1] for i in idx]))
                z = jnp.asarray(
                    np.asarray([self._replay[i][2] for i in idx],
                               np.float32))
                self.params, self.opt_state, jstats = self._jit_update(
                    self.params, self.opt_state, obs, pi, z)
            stats = {k: float(v) for k, v in jstats.items()}
        return {
            "num_env_steps_sampled_this_iter": n_steps,
            "replay_size": len(self._replay),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    # ---- evaluation helpers ----

    def compute_action(self, state, num_sims: Optional[int] = None):
        """Best move by search (deployment path)."""
        mcts = MCTS(self.game, self._evaluate, self.config["c_puct"],
                    rng=self._np_rng)
        counts = mcts.run(state, num_sims or self.config["num_sims"],
                          add_noise=False)
        return int(np.argmax(counts))

    def policy_action(self, state) -> int:
        """Raw-net argmax move (no search) — isolates what the NET
        learned for learning tests."""
        legal = self.game.legal_actions(state)
        logits, _ = self._evaluate(self.game.observation(state)[None])
        masked = np.full(self.game.num_actions, -np.inf)
        masked[legal] = logits[0][legal]
        return int(np.argmax(masked))

    def play_vs_random(self, episodes: int = 20, use_search: bool = False,
                       seed: int = 0) -> Dict[str, float]:
        """Pit the agent (as BOTH colors alternately) against a uniform
        random opponent; returns win/draw/loss rates."""
        g = self.game
        rng = np.random.default_rng(seed)
        w = d = losses = 0
        for ep in range(episodes):
            agent_player = 1 if ep % 2 == 0 else -1
            state = g.initial_state()
            while True:
                tv = g.terminal_value(state)
                if tv is not None:
                    mover = state[1]
                    # tv is for the player to move; translate to agent
                    res = tv if mover == agent_player else -tv
                    if res > 0:
                        w += 1
                    elif res == 0:
                        d += 1
                    else:
                        losses += 1
                    break
                if state[1] == agent_player:
                    a = (self.compute_action(state) if use_search
                         else self.policy_action(state))
                else:
                    a = int(rng.choice(g.legal_actions(state)))
                state = g.next_state(state, a)
        n = float(episodes)
        return {"win_rate": w / n, "draw_rate": d / n,
                "loss_rate": losses / n}

    def save_checkpoint(self) -> Dict[str, Any]:
        return {"params": jax.device_get(self.params),
                "target_params": jax.device_get(self.params),
                "opt_state": jax.device_get(self.opt_state),
                "iteration": self._iteration,
                "timesteps_total": self._timesteps_total}
