"""APPO — asynchronous PPO (IMPALA architecture + clipped surrogate).

Reference analogue: rllib/algorithms/appo/ (appo.py, appo_torch_policy.py)
— the IMPALA actor-learner decoupling (async samplers, learner thread,
V-trace off-policy correction) with PPO's clipped surrogate objective on
the V-trace advantages instead of the plain policy-gradient term.
"""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala import (IMPALA, IMPALAConfig,
                                             IMPALAPolicy)
from ray_tpu.rllib.sample_batch import SampleBatch


class APPOPolicy(IMPALAPolicy):
    def loss(self, params, batch):
        dist_inputs, values, target_logp, vs, pg_adv = \
            self._vtrace_terms(params, batch)
        # PPO clip on the V-trace advantages (reference:
        # appo_torch_policy.py loss — the "is_ratio"/clipped surrogate)
        clip = self.config.get("clip_param", 0.3)
        ratio = jnp.exp(target_logp - batch[SampleBatch.ACTION_LOGP])
        surrogate = jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * pg_adv)
        total, stats = self._assemble_loss(
            -jnp.mean(surrogate), dist_inputs, values, vs)
        stats["mean_is_ratio"] = jnp.mean(ratio)
        return total, stats


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self._config.update({
            "clip_param": 0.3,
        })


class APPO(IMPALA):
    _policy_cls = APPOPolicy
    _default_config_cls = APPOConfig
