"""MADDPG — multi-agent DDPG with centralized critics (Lowe et al.
2017).

Reference analogue: rllib/algorithms/maddpg/ (maddpg.py,
maddpg_tf_policy.py): each agent i has a decentralized actor
π_i(o_i) and a CENTRALIZED critic Q_i(s, a_1..a_n) that observes the
global state and every agent's action during training; execution uses
only the local actors. Like QMIX, joint transitions don't fit the
per-policy rollout split, so the algorithm owns its env loop.

TPU-first: per-agent parameters are STACKED on a leading agent axis
and the whole actor+critic update for all agents runs as one
``jax.vmap``-ed jitted program — N agents cost one compiled kernel
launch, not N Python iterations.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import AlgorithmConfig, LocalAlgorithm
from ray_tpu.rllib.env import Box, MultiAgentEnv, _BUILTIN_ENVS, make_env
from ray_tpu.rllib.replay_buffers import ReplayBuffer
from ray_tpu.rllib.sample_batch import SampleBatch


class MultiAgentTarget1D(MultiAgentEnv):
    """N agents on a line steer (velocity action in [-1,1]) toward the
    origin; team reward = -mean(x_i^2) — a minimal smooth cooperative
    continuous-control env (reference analogue: the MPE spread task
    used by maddpg tests, reduced to 1D)."""

    HORIZON = 25

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        config = config or {}
        self.num_agents = int(config.get("num_agents", 2))
        self.agent_ids = [f"agent_{i}" for i in range(self.num_agents)]
        self._rng = np.random.default_rng(config.get("seed"))
        self.observation_space = Box(-np.inf, np.inf, (1,))
        self.action_space = Box(-1.0, 1.0, (1,))
        self._x: Optional[np.ndarray] = None
        self._t = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._x = self._rng.uniform(-2.0, 2.0, self.num_agents)
        self._t = 0
        obs = {a: np.array([self._x[i]], np.float32)
               for i, a in enumerate(self.agent_ids)}
        return obs, {a: {} for a in self.agent_ids}

    def step(self, action_dict):
        for i, a in enumerate(self.agent_ids):
            v = float(np.clip(np.asarray(action_dict[a]).ravel()[0],
                              -1.0, 1.0))
            self._x[i] += 0.2 * v
        self._t += 1
        team_r = float(-np.mean(self._x ** 2))
        done = self._t >= self.HORIZON
        obs = {a: np.array([self._x[i]], np.float32)
               for i, a in enumerate(self.agent_ids)}
        rews = {a: team_r for a in self.agent_ids}
        terms = {a: False for a in self.agent_ids}
        truncs = {a: done for a in self.agent_ids}
        terms["__all__"] = False
        truncs["__all__"] = done
        return obs, rews, terms, truncs, {a: {} for a in self.agent_ids}


_BUILTIN_ENVS["MultiAgentTarget1D"] = MultiAgentTarget1D


class _Actor(nn.Module):
    act_dim: int
    hidden: int = 64

    @nn.compact
    def __call__(self, obs):
        x = nn.relu(nn.Dense(self.hidden)(obs))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return jnp.tanh(nn.Dense(self.act_dim)(x))


class _CentralCritic(nn.Module):
    hidden: int = 64

    @nn.compact
    def __call__(self, state, joint_act):
        x = jnp.concatenate([state, joint_act], axis=-1)
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(1)(x)[..., 0]


class MADDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MADDPG)
        self._config.update({
            "env": "MultiAgentTarget1D",
            "actor_lr": 3e-4,
            "critic_lr": 1e-3,
            "tau": 0.01,
            "exploration_noise": 0.3,
            "replay_buffer_capacity": 50_000,
            "learning_starts": 500,
            "train_batch_size": 128,
            "rollout_fragment_length": 50,
            "training_intensity": 2,
            # targets polyak-update every learn step with `tau` (no
            # hard-sync period knob, unlike DQN/QMIX/R2D2)
        })


class MADDPG(LocalAlgorithm):
    _default_config_cls = MADDPGConfig

    def setup(self, config):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = cfg = base
        self.env = make_env(cfg["env"], cfg.get("env_config"))
        if not isinstance(self.env, MultiAgentEnv):
            raise ValueError("MADDPG needs a MultiAgentEnv")
        if not isinstance(self.env.action_space, Box):
            raise ValueError("MADDPG is continuous-action only")
        self.agent_ids = list(self.env.agent_ids)
        self.n = len(self.agent_ids)
        self.obs_dim = int(np.prod(self.env.observation_space.shape))
        self.act_dim = int(np.prod(self.env.action_space.shape))
        self.low = np.asarray(self.env.action_space.low, np.float32)
        self.high = np.asarray(self.env.action_space.high, np.float32)

        self.actor = _Actor(self.act_dim)
        self.critic = _CentralCritic()
        self._rng = jax.random.PRNGKey(cfg.get("seed") or 0)
        ka, kc = jax.random.split(self._next_rng())
        # stacked per-agent params: every leaf gains a leading (n,) axis
        state_dim = self.n * self.obs_dim
        joint_dim = self.n * self.act_dim

        def init_one(i):
            a = self.actor.init(jax.random.fold_in(ka, i),
                                jnp.zeros((1, self.obs_dim)))["params"]
            c = self.critic.init(jax.random.fold_in(kc, i),
                                 jnp.zeros((1, state_dim)),
                                 jnp.zeros((1, joint_dim)))["params"]
            return {"actor": a, "critic": c}

        per_agent = [init_one(i) for i in range(self.n)]
        self.params = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *per_agent)
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        # slow actors against fast critics — the standard MADDPG
        # stabilization: each critic's target moves with the OTHER
        # agents' actors, so actor updates must trail critic fitting
        self.optimizer = optax.multi_transform(
            {"actor": optax.chain(optax.clip_by_global_norm(10.0),
                                  optax.adam(cfg["actor_lr"])),
             "critic": optax.chain(optax.clip_by_global_norm(10.0),
                                   optax.adam(cfg["critic_lr"]))},
            param_labels={"actor": "actor", "critic": "critic"})
        self.opt_state = self.optimizer.init(self.params)
        self._jit_act = jax.jit(self._act_impl)
        self._jit_update = jax.jit(self._update_impl)

        self.replay = ReplayBuffer(cfg["replay_buffer_capacity"],
                                   seed=cfg.get("seed"))
        self._init_local_state()
        self._obs, _ = self.env.reset(seed=cfg.get("seed"))
        self._episode_reward = 0.0

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ---- jitted programs ----

    def _act_impl(self, params, obs):
        """obs (n, do) -> per-agent deterministic actions (n, da)."""
        return jax.vmap(
            lambda p, o: self.actor.apply({"params": p}, o[None])[0]
        )(params["actor"], obs)

    def _update_impl(self, params, target_params, opt_state, batch):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        obs = batch["obs"]           # (B, n, do)
        nobs = batch["next_obs"]
        acts = batch["actions"]      # (B, n, da) in tanh space
        rews = batch["rewards"]      # (B,) team
        not_done = 1.0 - batch["dones"].astype(jnp.float32)
        b = obs.shape[0]
        state = obs.reshape(b, -1)
        nstate = nobs.reshape(b, -1)
        joint_act = acts.reshape(b, -1)

        # target joint action from all target actors: (B, n, da)
        next_a = jax.vmap(
            lambda p, o: self.actor.apply({"params": p}, o),
            in_axes=(0, 1), out_axes=1)(target_params["actor"], nobs)
        njoint = next_a.reshape(b, -1)

        def per_agent_critic_target(tc):
            return self.critic.apply({"params": tc}, nstate, njoint)
        tq = jax.vmap(per_agent_critic_target)(
            target_params["critic"])          # (n, B)
        y = jax.lax.stop_gradient(
            rews[None, :] + gamma * not_done[None, :] * tq)  # (n, B)

        def loss_fn(p):
            # critic: every agent's Q(s, a_all) regresses its target
            q = jax.vmap(
                lambda c: self.critic.apply({"params": c}, state,
                                            joint_act)
            )(p["critic"])                    # (n, B)
            critic_loss = jnp.mean((q - y) ** 2)

            # actor i: own action from π_i, others from the batch
            own = jax.vmap(
                lambda a, o: self.actor.apply({"params": a}, o),
                in_axes=(0, 1), out_axes=0)(p["actor"], obs)  # (n, B, da)
            idx = jnp.arange(self.n)

            def actor_q(i):
                mixed = acts.at[:, i].set(own[i])
                frozen = jax.lax.stop_gradient(
                    jax.tree_util.tree_map(lambda x: x[i], p["critic"]))
                return self.critic.apply({"params": frozen}, state,
                                         mixed.reshape(b, -1))
            actor_loss = -jnp.mean(jax.vmap(actor_q)(idx))
            total = critic_loss + actor_loss
            return total, {"critic_loss": critic_loss,
                           "actor_loss": actor_loss,
                           "mean_q": jnp.mean(q)}

        (loss_val, stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state,
                                                   params)
        params = optax.apply_updates(params, updates)
        tau = cfg.get("tau", 0.01)
        target_params = jax.tree_util.tree_map(
            lambda t, p_: (1 - tau) * t + tau * p_, target_params,
            params)
        stats = dict(stats)
        stats["loss"] = loss_val
        return params, target_params, opt_state, stats

    # ---- collection ----

    def _joint_actions(self, obs_dict, noise: float,
                       uniform: bool = False):
        obs = np.stack([obs_dict[a] for a in self.agent_ids])
        if uniform:
            # pure-random warmup decorrelates the agents' actions so
            # each centralized critic can attribute per-slot effects
            # (without it, early actor drift saturates every action at
            # ±1 and the joint-action landscape is unlearnable)
            raw = self._np_rng.uniform(
                -1.0, 1.0, (self.n, self.act_dim)).astype(np.float32)
        else:
            raw = np.asarray(self._jit_act(self.params,
                                           jnp.asarray(obs)))  # (n, da)
        if noise > 0 and not uniform:
            raw = np.clip(raw + self._np_rng.normal(
                0.0, noise, raw.shape).astype(np.float32), -1.0, 1.0)
        scaled = self.low + (raw + 1.0) * 0.5 * (self.high - self.low)
        return ({a: scaled[i] for i, a in enumerate(self.agent_ids)},
                raw)

    def _collect(self, num_steps: int, noise: float) -> int:
        warmup = len(self.replay) < self.config["learning_starts"]

        def act(obs_dict):
            return self._joint_actions(obs_dict, noise, uniform=warmup)

        return self._collect_joint(act, num_steps)

    # ---- Algorithm surface ----

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = self._collect(cfg["rollout_fragment_length"],
                          cfg["exploration_noise"])
        self._timesteps_total += n
        stats: Dict[str, float] = {}
        if len(self.replay) >= cfg["learning_starts"]:
            for _ in range(max(1, cfg.get("training_intensity", 1))):
                train = self.replay.sample(cfg["train_batch_size"])
                jbatch = {k: jnp.asarray(v) for k, v in train.items()
                          if isinstance(v, np.ndarray)
                          and v.dtype != object}
                (self.params, self.target_params, self.opt_state,
                 jstats) = self._jit_update(
                    self.params, self.target_params, self.opt_state,
                    jbatch)
                stats = {k: float(v) for k, v in jstats.items()}
        return {
            "num_env_steps_sampled_this_iter": n,
            "replay_size": len(self.replay),
            **{f"learner/{k}": v for k, v in stats.items()},
        }

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        out = self._eval_episodes(
            lambda obs: self._joint_actions(obs, noise=0.0)[0],
            num_episodes, seed_base=30_000)
        self._obs, _ = self.env.reset()
        self._episode_reward = 0.0
        return out
