"""Ape-X DDPG — distributed prioritized replay for continuous control.

Reference analogue: rllib/algorithms/apex_ddpg/apex_ddpg.py, which reuses
ApexDQN's training_step with the DDPG policy — exactly the composition
here via ApexLoopMixin. The exploration ladder scales per-worker Gaussian
action noise instead of epsilon; priorities come from the critic's
per-sample |TD| (ddpg.py critic stats ``td_errors``); target networks
polyak-update inside learn_on_batch, so the mixin's hard-sync is skipped.
"""

from __future__ import annotations

from ray_tpu.rllib.algorithms.apex_dqn import ApexLoopMixin
from ray_tpu.rllib.algorithms.ddpg import DDPG, DDPGConfig


class ApexDDPGConfig(DDPGConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDDPG)
        self._config.update({
            "num_workers": 2,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
            "exploration_noise": 0.4,  # ladder base, per-worker scaled
            "replay_prefetch": 2,
            "train_batch_size": 64,
            "rollout_fragment_length": 16,
            "learning_starts": 500,
            "max_sample_batches_per_iter": 8,
            "train_intensity_per_iter": 4,
        })


class ApexDDPG(ApexLoopMixin, DDPG):
    _default_config_cls = ApexDDPGConfig

    def _worker_exploration(self, i, n):
        # same geometric ladder as Ape-X epsilon, applied to noise scale
        base = self.config.get("exploration_noise", 0.4)
        return {"exploration_noise": base ** (1 + 7 * i / max(1, n - 1))}

    def setup(self, config):
        super().setup(config)
        self._apex_setup()
        # learner policy acts greedily (it never samples the env)
        self.workers.local_worker.policy.exploration_noise = 0.0
