from ray_tpu.rllib.execution.learner_thread import LearnerThread  # noqa: F401
