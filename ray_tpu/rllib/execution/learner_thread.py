"""Asynchronous learner thread: decouples device updates from sampling.

Reference analogue: rllib/execution/learner_thread.py:15 (and the
multi_gpu variant) — the defining IMPALA structure: actors keep sampling
while the learner drains a bounded in-memory queue. Here the "device" is
the jitted learn_on_batch program (TPU or CPU); one dedicated thread owns
all calls into it so XLA execution is single-threaded, and weight reads
for broadcast synchronize on a lock.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Optional


class LearnerThread(threading.Thread):
    def __init__(self, policy, max_queue_size: int = 16):
        super().__init__(daemon=True, name="rllib-learner")
        self.policy = policy
        self.inqueue: "queue.Queue" = queue.Queue(maxsize=max_queue_size)
        self.weights_lock = threading.Lock()
        self.stopped = False
        self.num_steps = 0
        self.num_samples_trained = 0
        self.learn_time_total = 0.0
        self.queue_wait_total = 0.0
        self.stats: Dict[str, Any] = {}
        self._error: Optional[BaseException] = None

    def run(self):
        while not self.stopped:
            try:
                t0 = time.perf_counter()
                batch = self.inqueue.get(timeout=0.2)
                self.queue_wait_total += time.perf_counter() - t0
            except queue.Empty:
                continue
            if batch is None:
                break
            try:
                t1 = time.perf_counter()
                with self.weights_lock:
                    self.stats = self.policy.learn_on_batch(batch)
                self.learn_time_total += time.perf_counter() - t1
                self.num_steps += 1
                self.num_samples_trained += batch.count
            except BaseException as e:  # surfaced by training_step
                self._error = e
                self.stopped = True

    # ---- driver-side API ----

    def put(self, batch, timeout: float = 60.0) -> bool:
        """Enqueue a batch; False if the learner is saturated (caller
        should apply backpressure by not relaunching that sampler yet)."""
        self.check_error()
        try:
            self.inqueue.put(batch, timeout=timeout)
            return True
        except queue.Full:
            return False

    def get_weights(self):
        with self.weights_lock:
            return self.policy.get_weights()

    def check_error(self):
        if self._error is not None:
            raise RuntimeError("learner thread died") from self._error

    def stop(self):
        self.stopped = True
        try:
            self.inqueue.put_nowait(None)
        except queue.Full:
            pass

    def metrics(self) -> Dict[str, Any]:
        return {
            "learner_queue_size": self.inqueue.qsize(),
            "num_learner_steps": self.num_steps,
            "num_samples_trained": self.num_samples_trained,
            "learn_time_total_s": self.learn_time_total,
            "learner_queue_wait_total_s": self.queue_wait_total,
        }
