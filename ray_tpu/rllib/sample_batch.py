"""SampleBatch / MultiAgentBatch — columnar trajectory storage.

Reference analogue: rllib/policy/sample_batch.py:125 (SampleBatch) and
:1164 (MultiAgentBatch). TPU-first differences: batches are plain numpy
column dicts with *fixed-shape discipline* — ``to_device`` pads/buckets so
repeated learner steps hit the XLA compile cache instead of recompiling.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import numpy as np

OBS = "obs"
NEXT_OBS = "new_obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
TRUNCATEDS = "truncateds"
INFOS = "infos"
EPS_ID = "eps_id"
ACTION_LOGP = "action_logp"
ACTION_DIST_INPUTS = "action_dist_inputs"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
SEQ_LENS = "seq_lens"


class SampleBatch(dict):
    """A dict of equal-length numpy columns holding trajectory data."""

    # Re-export column names on the class, as the reference does.
    OBS = OBS
    NEXT_OBS = NEXT_OBS
    ACTIONS = ACTIONS
    REWARDS = REWARDS
    DONES = DONES
    TRUNCATEDS = TRUNCATEDS
    INFOS = INFOS
    EPS_ID = EPS_ID
    ACTION_LOGP = ACTION_LOGP
    ACTION_DIST_INPUTS = ACTION_DIST_INPUTS
    VF_PREDS = VF_PREDS
    ADVANTAGES = ADVANTAGES
    VALUE_TARGETS = VALUE_TARGETS
    SEQ_LENS = SEQ_LENS

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if isinstance(v, list):
                self[k] = np.asarray(v)

    @property
    def count(self) -> int:
        for v in self.values():
            if hasattr(v, "__len__"):
                return len(v)
        return 0

    def __len__(self) -> int:  # len(batch) == row count, as in the reference
        return self.count

    # ---- construction ----

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        batches = [b for b in batches if b is not None and b.count > 0]
        if not batches:
            return SampleBatch()
        keys = set(batches[0].keys())
        for b in batches[1:]:
            keys &= set(b.keys())
        out = {}
        for k in keys:
            out[k] = np.concatenate([np.asarray(b[k]) for b in batches],
                                    axis=0)
        return SampleBatch(out)

    def concat(self, other: "SampleBatch") -> "SampleBatch":
        return SampleBatch.concat_samples([self, other])

    def copy(self) -> "SampleBatch":
        return SampleBatch({k: np.copy(v) for k, v in self.items()})

    # ---- slicing / iteration ----

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def __getitem__(self, key):
        if isinstance(key, slice):
            return self.slice(key.start or 0,
                              key.stop if key.stop is not None else self.count)
        return super().__getitem__(key)

    def shuffle(self, rng: Optional[np.random.Generator] = None
                ) -> "SampleBatch":
        rng = rng or np.random.default_rng()
        perm = rng.permutation(self.count)
        return SampleBatch({k: np.asarray(v)[perm] for k, v in self.items()})

    def minibatches(self, minibatch_size: int,
                    shuffle: bool = True,
                    rng: Optional[np.random.Generator] = None
                    ) -> Iterator["SampleBatch"]:
        """Yield fixed-size minibatches (drops the ragged tail so every
        learner step has an identical shape → one XLA compilation)."""
        b = self.shuffle(rng) if shuffle else self
        n = (b.count // minibatch_size) * minibatch_size
        for i in range(0, n, minibatch_size):
            yield b.slice(i, i + minibatch_size)

    # ---- shape discipline ----

    def pad_to(self, size: int) -> "SampleBatch":
        """Pad every column to ``size`` rows (repeat-last padding) so the
        batch fits a single bucketed XLA program shape."""
        n = self.count
        if n >= size:
            return self.slice(0, size)
        out = {}
        for k, v in self.items():
            v = np.asarray(v)
            pad = np.repeat(v[-1:], size - n, axis=0)
            out[k] = np.concatenate([v, pad], axis=0)
        out["_valid_mask"] = np.concatenate(
            [np.ones(n, np.float32), np.zeros(size - n, np.float32)])
        return SampleBatch(out)

    def split_by_episode(self) -> List["SampleBatch"]:
        if EPS_ID not in self:
            return [self]
        ids = np.asarray(self[EPS_ID])
        cuts = np.where(ids[1:] != ids[:-1])[0] + 1
        bounds = [0, *cuts.tolist(), len(ids)]
        return [self.slice(a, b) for a, b in zip(bounds[:-1], bounds[1:])]

    def total_reward(self) -> float:
        return float(np.sum(self.get(REWARDS, 0.0)))


class MultiAgentBatch:
    """Policy-id → SampleBatch mapping (reference: sample_batch.py:1164)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch],
                 env_steps: int):
        self.policy_batches = policy_batches
        self._env_steps = env_steps

    @property
    def count(self) -> int:
        return self._env_steps

    def env_steps(self) -> int:
        return self._env_steps

    def agent_steps(self) -> int:
        return sum(b.count for b in self.policy_batches.values())

    @staticmethod
    def concat_samples(batches: List["MultiAgentBatch"]) -> "MultiAgentBatch":
        out: Dict[str, List[SampleBatch]] = {}
        steps = 0
        for mb in batches:
            steps += mb.env_steps()
            for pid, b in mb.policy_batches.items():
                out.setdefault(pid, []).append(b)
        return MultiAgentBatch(
            {pid: SampleBatch.concat_samples(bs) for pid, bs in out.items()},
            steps)


def convert_ma_batch_to_sample_batch(batch: Any) -> SampleBatch:
    if isinstance(batch, MultiAgentBatch):
        if len(batch.policy_batches) == 1:
            return next(iter(batch.policy_batches.values()))
        return SampleBatch.concat_samples(
            list(batch.policy_batches.values()))
    return batch
