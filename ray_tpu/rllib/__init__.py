"""ray_tpu.rllib — reinforcement learning on JAX/TPU.

Reference analogue: rllib/ (Algorithm, RolloutWorker/WorkerSet,
SampleBatch, policies, replay buffers). Policies are flax modules with
jitted losses (PPO clipped surrogate, IMPALA V-trace, DQN double-Q);
rollouts run on CPU actors with one batched jitted forward per vector-env
step.
"""

from ray_tpu.rllib.sample_batch import (MultiAgentBatch, SampleBatch,
                                        convert_ma_batch_to_sample_batch)
from ray_tpu.rllib.env import (Box, CartPoleEnv, Discrete,
                               MultiAgentCartPole, MultiAgentEnv,
                               PendulumEnv, VectorEnv, make_env)
from ray_tpu.rllib.connectors import (ClipActionConnector, Connector,
                                      ConnectorPipeline,
                                      FlattenObsConnector,
                                      LambdaConnector, MeanStdObsConnector)
from ray_tpu.rllib.models import MLPNet, AtariCNN, make_model
from ray_tpu.rllib.policy import JaxPolicy
from ray_tpu.rllib.core import (Learner, LearnerGroup, MultiRLModule,
                                PPOLearner, RLModule, RLModuleSpec)
from ray_tpu.rllib.postprocessing import compute_advantages
from ray_tpu.rllib.replay_buffers import (PrioritizedReplayBuffer,
                                          ReplayBuffer)
from ray_tpu.rllib.rollout_worker import (MultiAgentRolloutWorker,
                                          RolloutWorker, WorkerSet,
                                          synchronous_parallel_sample)
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms import (DQN, DQNConfig, IMPALA, IMPALAConfig,
                                      PPO, PPOConfig)

__all__ = [
    "SampleBatch", "MultiAgentBatch", "convert_ma_batch_to_sample_batch",
    "Box", "Discrete", "CartPoleEnv", "PendulumEnv", "VectorEnv",
    "make_env", "MLPNet", "AtariCNN", "make_model", "JaxPolicy",
    "compute_advantages", "ReplayBuffer", "PrioritizedReplayBuffer",
    "RolloutWorker", "MultiAgentRolloutWorker", "WorkerSet",
    "synchronous_parallel_sample",
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "DQN",
    "DQNConfig", "IMPALA", "IMPALAConfig",
    "MultiAgentEnv", "MultiAgentCartPole",
    "Connector", "ConnectorPipeline", "FlattenObsConnector",
    "MeanStdObsConnector", "ClipActionConnector", "LambdaConnector",
    "RLModule", "RLModuleSpec", "MultiRLModule", "Learner",
    "PPOLearner", "LearnerGroup",
]
