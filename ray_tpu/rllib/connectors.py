"""Connector pipelines: composable obs/action transforms between env and
policy.

Reference analogue: rllib/connectors/ (agent + action connectors,
connector_pipeline_v2.py). A pipeline of small pure transforms applied
worker-side: agent connectors on observations BEFORE the policy forward,
action connectors on actions AFTER it — so preprocessing lives with the
sampling worker and is identical at train and serve time.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Connector:
    def __call__(self, data: np.ndarray) -> np.ndarray:
        """Transform AND update any running state (training-time path)."""
        raise NotImplementedError

    def transform(self, data: np.ndarray) -> np.ndarray:
        """Transform WITHOUT updating state — for terminal/bootstrap
        observations and inference, where the data must not be counted
        twice into running statistics."""
        return self(data)

    def state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]):
        pass


class LambdaConnector(Connector):
    def __init__(self, fn: Callable[[np.ndarray], np.ndarray],
                 name: str = "lambda"):
        self.fn = fn
        self.name = name

    def __call__(self, data):
        return self.fn(data)


class FlattenObsConnector(Connector):
    """[B, ...] -> [B, prod(...)] (reference: FlattenObservations)."""

    def __call__(self, obs):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)


class MeanStdObsConnector(Connector):
    """Running mean/std observation normalization (reference:
    MeanStdFilter agent connector). State ships with checkpoints."""

    def __init__(self, epsilon: float = 1e-8):
        self.eps = epsilon
        self._count = 0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs):
        obs = np.asarray(obs, np.float64)
        for row in obs:
            self._count += 1
            if self._mean is None:
                self._mean = np.zeros_like(row)
                self._m2 = np.zeros_like(row)
            delta = row - self._mean
            self._mean += delta / self._count
            self._m2 += delta * (row - self._mean)
        return self.transform(obs)

    def transform(self, obs):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            return obs.astype(np.float32)
        std = np.sqrt(self._m2 / max(1, self._count - 1)) \
            if self._count > 1 else np.ones_like(self._mean)
        return ((obs - self._mean) / (std + self.eps)).astype(np.float32)

    def state(self):
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state):
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class ClipActionConnector(Connector):
    """Clip continuous actions into [low, high] (reference:
    clip_actions action connector)."""

    def __init__(self, low, high):
        self.low, self.high = low, high

    def __call__(self, actions):
        return np.clip(actions, self.low, self.high)


class ConnectorPipeline:
    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors = list(connectors or [])

    def __call__(self, data):
        for c in self.connectors:
            data = c(data)
        return data

    def transform(self, data):
        """State-preserving application (see Connector.transform)."""
        for c in self.connectors:
            data = c.transform(data)
        return data

    def append(self, connector: Connector):
        self.connectors.append(connector)

    def state(self) -> List[Dict[str, Any]]:
        return [c.state() for c in self.connectors]

    def set_state(self, states: List[Dict[str, Any]]):
        for c, s in zip(self.connectors, states):
            c.set_state(s)


def build_connectors(config: Dict[str, Any]):
    """(obs_pipeline, action_pipeline) from config["connectors"]:
    {"obs": [Connector...], "actions": [Connector...]}."""
    spec = config.get("connectors") or {}
    return (ConnectorPipeline(spec.get("obs")),
            ConnectorPipeline(spec.get("actions")))
