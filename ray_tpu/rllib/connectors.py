"""Connector pipelines: composable obs/action transforms between env and
policy.

Reference analogue: rllib/connectors/ (agent + action connectors,
connector_pipeline_v2.py). A pipeline of small pure transforms applied
worker-side: agent connectors on observations BEFORE the policy forward,
action connectors on actions AFTER it — so preprocessing lives with the
sampling worker and is identical at train and serve time.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Connector:
    def __call__(self, data: np.ndarray,
                 dones: Optional[np.ndarray] = None) -> np.ndarray:
        """Transform AND update any running state (training-time path).
        ``dones[i]`` marks rows whose sub-env auto-reset THIS step —
        stateful per-env connectors (frame stacking) restart those
        slots."""
        raise NotImplementedError

    def transform(self, data: np.ndarray) -> np.ndarray:
        """Transform WITHOUT updating state — for terminal/bootstrap
        observations and inference, where the data must not be counted
        twice into running statistics."""
        return self(data)

    def output_space(self, space):
        """Observation space AFTER this transform (the policy is built
        against the pipeline's output, not the raw env space)."""
        return space

    def clone_for_eval(self) -> "Connector":
        """A fresh-state copy for a single-env evaluation episode;
        running-statistics connectors share state (stats must match
        training), per-episode-state connectors restart."""
        return self

    def state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]):
        pass


class LambdaConnector(Connector):
    def __init__(self, fn: Callable[[np.ndarray], np.ndarray],
                 name: str = "lambda"):
        self.fn = fn
        self.name = name

    def __call__(self, data, dones=None):
        return self.fn(data)


class FlattenObsConnector(Connector):
    """[B, ...] -> [B, prod(...)] (reference: FlattenObservations)."""

    def __call__(self, obs, dones=None):
        obs = np.asarray(obs)
        return obs.reshape(obs.shape[0], -1)

    def output_space(self, space):
        from ray_tpu.rllib.env import Box
        return Box(-np.inf, np.inf, (int(np.prod(space.shape)),))


class MeanStdObsConnector(Connector):
    """Running mean/std observation normalization (reference:
    MeanStdFilter agent connector). State ships with checkpoints."""

    def __init__(self, epsilon: float = 1e-8):
        self.eps = epsilon
        self._count = 0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def __call__(self, obs, dones=None):
        obs = np.asarray(obs, np.float64)
        for row in obs:
            self._count += 1
            if self._mean is None:
                self._mean = np.zeros_like(row)
                self._m2 = np.zeros_like(row)
            delta = row - self._mean
            self._mean += delta / self._count
            self._m2 += delta * (row - self._mean)
        return self.transform(obs)

    def transform(self, obs):
        obs = np.asarray(obs, np.float64)
        if self._mean is None:
            return obs.astype(np.float32)
        std = np.sqrt(self._m2 / max(1, self._count - 1)) \
            if self._count > 1 else np.ones_like(self._mean)
        return ((obs - self._mean) / (std + self.eps)).astype(np.float32)

    def clone_for_eval(self):
        # frozen view: eval episodes read the training stats but must
        # not feed them
        return LambdaConnector(self.transform, name="frozen_meanstd")

    def state(self):
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state):
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class ClipActionConnector(Connector):
    """Clip continuous actions into [low, high] (reference:
    clip_actions action connector)."""

    def __init__(self, low, high):
        self.low, self.high = low, high

    def __call__(self, actions, dones=None):
        return np.clip(actions, self.low, self.high)


class GrayscaleObsConnector(Connector):
    """[B, H, W, C] -> [B, H, W, 1] luminance mean (reference: the
    atari_wrappers.py WarpFrame grayscale half)."""

    def __call__(self, obs, dones=None):
        obs = np.asarray(obs)
        return obs.mean(axis=-1, keepdims=True).astype(obs.dtype)

    def output_space(self, space):
        from ray_tpu.rllib.env import Box
        h, w = space.shape[0], space.shape[1]
        return Box(0, 255, (h, w, 1), np.uint8)


class ResizeObsConnector(Connector):
    """[B, H, W, C] -> [B, h, w, C] by integer-factor average pooling
    (reference: atari_wrappers.py WarpFrame resize — cv2-free)."""

    def __init__(self, height: int, width: int):
        self.h, self.w = height, width

    def __call__(self, obs, dones=None):
        obs = np.asarray(obs)
        b, H, W, c = obs.shape
        fh, fw = H // self.h, W // self.w
        if fh * self.h != H or fw * self.w != W:
            raise ValueError(
                f"resize {H}x{W} -> {self.h}x{self.w}: factors must "
                "be integers")
        pooled = obs.reshape(b, self.h, fh, self.w, fw, c).mean((2, 4))
        return pooled.astype(obs.dtype)

    def output_space(self, space):
        from ray_tpu.rllib.env import Box
        return Box(0, 255, (self.h, self.w, space.shape[-1]), np.uint8)


class FrameStackConnector(Connector):
    """[B, H, W, C] -> [B, H, W, C*k]: per-sub-env stacks of the last k
    frames along the channel axis (reference: atari_wrappers.py
    FrameStack). A slot whose episode auto-reset this step (``dones``)
    restarts its stack from the fresh observation."""

    def __init__(self, k: int = 4):
        self.k = k
        self._stacks: Optional[np.ndarray] = None  # [B, H, W, C*k]
        self._c = 0

    def _restart(self, obs_row):
        return np.concatenate([obs_row] * self.k, axis=-1)

    def __call__(self, obs, dones=None):
        obs = np.asarray(obs)
        if self._stacks is None or self._stacks.shape[0] != obs.shape[0]:
            self._c = obs.shape[-1]
            self._stacks = np.stack(
                [self._restart(o) for o in obs])
            return self._stacks.copy()
        shifted = np.concatenate(
            [self._stacks[..., self._c:], obs], axis=-1)
        if dones is not None:
            for i in np.nonzero(np.asarray(dones))[0]:
                shifted[i] = self._restart(obs[i])
        self._stacks = shifted
        return self._stacks.copy()

    def transform(self, obs):
        """Append to the CURRENT stacks without advancing state — the
        terminal/bootstrap observation path."""
        obs = np.asarray(obs)
        if self._stacks is None or self._stacks.shape[0] != obs.shape[0]:
            return np.concatenate([obs] * self.k, axis=-1)
        return np.concatenate([self._stacks[..., obs.shape[-1]:], obs],
                              axis=-1)

    def output_space(self, space):
        from ray_tpu.rllib.env import Box
        h, w, c = space.shape
        return Box(0, 255, (h, w, c * self.k), np.uint8)

    def clone_for_eval(self):
        return FrameStackConnector(self.k)


class ConnectorPipeline:
    def __init__(self, connectors: Optional[List[Connector]] = None):
        self.connectors = list(connectors or [])

    def __call__(self, data, dones=None):
        for c in self.connectors:
            data = c(data, dones)
        return data

    def transform(self, data):
        """State-preserving application (see Connector.transform)."""
        for c in self.connectors:
            data = c.transform(data)
        return data

    def observation_space(self, space):
        for c in self.connectors:
            space = c.output_space(space)
        return space

    def clone_for_eval(self) -> "ConnectorPipeline":
        return ConnectorPipeline(
            [c.clone_for_eval() for c in self.connectors])

    def append(self, connector: Connector):
        self.connectors.append(connector)

    def state(self) -> List[Dict[str, Any]]:
        return [c.state() for c in self.connectors]

    def set_state(self, states: List[Dict[str, Any]]):
        for c, s in zip(self.connectors, states):
            c.set_state(s)


def build_connectors(config: Dict[str, Any]):
    """(obs_pipeline, action_pipeline) from config["connectors"]:
    {"obs": [Connector...], "actions": [Connector...]}."""
    spec = config.get("connectors") or {}
    return (ConnectorPipeline(spec.get("obs")),
            ConnectorPipeline(spec.get("actions")))
