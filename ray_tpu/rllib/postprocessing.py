"""Advantage estimation (GAE) — reference: rllib/evaluation/postprocessing.py
compute_advantages/compute_gae_for_sample_batch.

Host-side numpy implementation operating per-trajectory fragment; the
learner-side losses consume the resulting ADVANTAGES/VALUE_TARGETS columns.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.sample_batch import (
    ADVANTAGES, DONES, REWARDS, SampleBatch, TRUNCATEDS, VALUE_TARGETS,
    VF_PREDS)


def compute_advantages(batch: SampleBatch, last_value: float,
                       gamma: float = 0.99, lambda_: float = 1.0,
                       use_gae: bool = True,
                       standardize: bool = False) -> SampleBatch:
    """Append GAE advantages + value targets to a trajectory fragment.

    ``last_value`` bootstraps the value beyond the fragment (0 if the
    episode terminated).
    """
    rewards = np.asarray(batch[REWARDS], np.float32)
    n = len(rewards)
    if use_gae:
        vf = np.asarray(batch[VF_PREDS], np.float32)
        vf_next = np.concatenate([vf[1:], [np.float32(last_value)]])
        deltas = rewards + gamma * vf_next - vf
        adv = np.zeros(n, np.float32)
        acc = 0.0
        for t in range(n - 1, -1, -1):
            acc = deltas[t] + gamma * lambda_ * acc
            adv[t] = acc
        batch[ADVANTAGES] = adv
        batch[VALUE_TARGETS] = adv + vf
    else:
        returns = np.zeros(n, np.float32)
        acc = float(last_value)
        for t in range(n - 1, -1, -1):
            acc = rewards[t] + gamma * acc
            returns[t] = acc
        batch[ADVANTAGES] = returns
        batch[VALUE_TARGETS] = returns
    if standardize:
        a = batch[ADVANTAGES]
        batch[ADVANTAGES] = (a - a.mean()) / max(1e-4, a.std())
    return batch


def compute_gae_for_sample_batch(policy, batch: SampleBatch,
                                 gamma: float, lambda_: float
                                 ) -> SampleBatch:
    """Bootstrap from the policy's value function unless the fragment ended
    in a true terminal (reference: postprocessing.py:168)."""
    terminated = bool(batch[DONES][-1]) and not bool(
        batch.get(TRUNCATEDS, np.zeros(len(batch)))[-1])
    if terminated:
        last_value = 0.0
    else:
        last_value = float(policy.value(batch[SampleBatch.NEXT_OBS][-1:])[0])
    return compute_advantages(batch, last_value, gamma, lambda_)
