"""JaxPolicy — the TPU-native policy abstraction.

Reference analogue: rllib/policy/torch_policy_v2.py:62 (compute_actions
:499, loss :212, learn_on_batch :603). Differences by design:

- ``compute_actions`` is ONE jitted batched forward over the whole vector
  env (no per-env Python loop).
- ``learn_on_batch`` is a single jitted (loss → grad → optax update)
  program with donated optimizer/param state; minibatch SGD epochs run as
  repeated calls into the same compiled program (fixed shapes).
- Weights are pytrees; ``get_weights`` pulls to host numpy for object-store
  broadcast to rollout workers (reference: WorkerSet.sync_weights).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.env import Discrete
from ray_tpu.rllib.models import (
    categorical_entropy, categorical_logp, categorical_sample,
    diag_gaussian_entropy, diag_gaussian_logp, diag_gaussian_sample,
    make_model)
from ray_tpu.rllib.sample_batch import SampleBatch


def _stats_to_host(stats: Dict[str, Any]) -> Dict[str, Any]:
    """Scalars → python floats; per-sample arrays (e.g. TD errors for
    prioritized replay) stay as numpy."""
    out = {}
    for k, v in stats.items():
        if getattr(v, "ndim", 0) == 0:
            out[k] = float(v)
        else:
            out[k] = np.asarray(v)
    return out


class JaxPolicy:
    """A policy = flax model + action distribution + optax optimizer +
    a jitted loss. Subclasses override :meth:`loss`."""

    def __init__(self, obs_space, action_space, config: Dict[str, Any]):
        self.observation_space = obs_space
        self.action_space = action_space
        self.config = config
        self.discrete = isinstance(action_space, Discrete)
        self.model = make_model(obs_space, action_space,
                                config.get("model"))
        seed = config.get("seed") or 0
        self._rng = jax.random.PRNGKey(seed)
        obs_dim = obs_space.shape or (1,)
        dummy = jnp.zeros((1, *obs_dim), jnp.float32)
        self.params = self.model.init(self._next_rng(), dummy)["params"]
        self.optimizer = self._make_optimizer()
        self.opt_state = self.optimizer.init(self.params)
        self._jit_actions = jax.jit(self._compute_actions_impl)
        self._jit_update = jax.jit(self._update_impl, donate_argnums=(0, 1))
        self._jit_value = jax.jit(self._value_impl)
        self.global_timestep = 0

    # ---- wiring ----

    def _make_optimizer(self):
        lr = self.config.get("lr", 5e-5)
        clip = self.config.get("grad_clip")
        chain = []
        if clip:
            chain.append(optax.clip_by_global_norm(clip))
        chain.append(optax.adam(lr))
        return optax.chain(*chain)

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ---- inference ----

    def _compute_actions_impl(self, params, obs, rng, explore):
        dist_inputs, vf = self.model.apply({"params": params}, obs)
        if self.discrete:
            stoch = categorical_sample(rng, dist_inputs)
            greedy = jnp.argmax(dist_inputs, axis=-1)
            actions = jnp.where(explore, stoch, greedy)
            logp = categorical_logp(dist_inputs, actions)
        else:
            stoch = diag_gaussian_sample(rng, dist_inputs)
            greedy, _ = jnp.split(dist_inputs, 2, axis=-1)
            actions = jnp.where(explore, stoch, greedy)
            logp = diag_gaussian_logp(dist_inputs, actions)
        return actions, logp, dist_inputs, vf

    def compute_actions(self, obs: np.ndarray, explore: bool = True
                        ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        obs = jnp.asarray(obs)
        actions, logp, dist_inputs, vf = self._jit_actions(
            self.params, obs, self._next_rng(), explore)
        extras = {
            SampleBatch.ACTION_LOGP: np.asarray(logp),
            SampleBatch.ACTION_DIST_INPUTS: np.asarray(dist_inputs),
            SampleBatch.VF_PREDS: np.asarray(vf),
        }
        return np.asarray(actions), extras

    def _value_impl(self, params, obs):
        _, vf = self.model.apply({"params": params}, obs)
        return vf

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.asarray(self._jit_value(self.params, jnp.asarray(obs)))

    # ---- action-dist helpers usable inside jitted losses ----

    def dist_logp(self, dist_inputs, actions):
        if self.discrete:
            return categorical_logp(dist_inputs, actions)
        return diag_gaussian_logp(dist_inputs, actions)

    def dist_entropy(self, dist_inputs):
        if self.discrete:
            return categorical_entropy(dist_inputs)
        return diag_gaussian_entropy(dist_inputs)

    # ---- learning ----

    def postprocess_trajectory(self, batch: SampleBatch) -> SampleBatch:
        """Per-episode-fragment hook run worker-side after sampling
        (reference: Policy.postprocess_trajectory). Default: no-op;
        PPO overrides to compute GAE."""
        return batch

    def loss(self, params, batch: Dict[str, jnp.ndarray]
             ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        """Return (scalar loss, stats dict). Traced under jit — must be
        pure, fixed-shape, no Python control flow on traced values."""
        raise NotImplementedError

    def _update_impl(self, params, opt_state, batch):
        (loss_val, stats), grads = jax.value_and_grad(
            self.loss, has_aux=True)(params, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        stats = dict(stats)
        stats["total_loss"] = loss_val
        stats["grad_gnorm"] = optax.global_norm(grads)
        return params, opt_state, stats

    def learn_on_batch(self, batch: SampleBatch) -> Dict[str, float]:
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                  if isinstance(v, np.ndarray) and v.dtype != object}
        self.params, self.opt_state, stats = self._jit_update(
            self.params, self.opt_state, jbatch)
        self.global_timestep += batch.count
        return _stats_to_host(stats)

    # ---- split grad computation/application (reference: policy.py
    # compute_gradients/apply_gradients — the A3C-style decomposition
    # where rollout workers compute grads and a learner applies them) ----

    def _grads_impl(self, params, batch):
        (loss_val, stats), grads = jax.value_and_grad(
            self.loss, has_aux=True)(params, batch)
        stats = dict(stats)
        stats["total_loss"] = loss_val
        return grads, stats

    def compute_gradients(self, batch: SampleBatch):
        """Worker-side half: returns (host-numpy grad pytree, stats) —
        shippable through the object store to the learner."""
        jbatch = {k: jnp.asarray(v) for k, v in batch.items()
                  if isinstance(v, np.ndarray) and v.dtype != object}
        if not hasattr(self, "_jit_grads"):
            self._jit_grads = jax.jit(self._grads_impl)
        grads, stats = self._jit_grads(self.params, jbatch)
        return jax.device_get(grads), _stats_to_host(stats)

    def apply_gradients(self, grads):
        """Learner-side half: one optax update from externally computed
        grads (same chain as ``learn_on_batch``, so clipping applies)."""
        grads = jax.tree_util.tree_map(jnp.asarray, grads)
        updates, self.opt_state = self.optimizer.update(
            grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)

    # ---- weights ----

    def get_weights(self) -> Dict[str, Any]:
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_weights(self, weights: Dict[str, Any]):
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)

    def get_state(self) -> Dict[str, Any]:
        return {
            "weights": self.get_weights(),
            "opt_state": jax.device_get(self.opt_state),
            "global_timestep": self.global_timestep,
        }

    def set_state(self, state: Dict[str, Any]):
        self.set_weights(state["weights"])
        self.opt_state = jax.tree_util.tree_map(
            jnp.asarray, state["opt_state"],
            is_leaf=lambda x: isinstance(x, (np.ndarray, np.generic)))
        self.global_timestep = state.get("global_timestep", 0)
