"""LearnerGroup — data-parallel learners.

Reference analogue: rllib/core/learner/learner_group.py — N learner
workers update one logical module set in data parallel.  The reference
rides torch DDP/NCCL; here the TPU-first story is: MULTI-CHIP data
parallelism belongs INSIDE one jitted program on a jax Mesh (see
train/spmd.py — that is how a pod trains), so the multi-WORKER group
exists for the reference-parity topology: learner actors on separate
hosts/processes, gradients averaged through the object store
(star reduce), every learner applying the same averaged update so
replicas stay bit-identical.

local mode (num_learners=0) runs the learner inline — the default for
single-host training and for tests.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.learner import Learner


def _avg_pytrees(trees: List[Any]):
    import jax
    n = len(trees)
    return jax.tree.map(lambda *xs: sum(np.asarray(x) for x in xs) / n,
                        *trees)


class LearnerGroup:
    def __init__(self, learner_cls, *, num_learners: int = 0,
                 learner_kwargs: Optional[Dict[str, Any]] = None):
        self._kwargs = dict(learner_kwargs or {})
        self._local: Optional[Learner] = None
        self._workers = []
        if num_learners <= 0:
            self._local = learner_cls(**self._kwargs)
        else:
            remote_cls = ray_tpu.remote(learner_cls)
            self._workers = [remote_cls.remote(**self._kwargs)
                             for _ in range(num_learners)]
            # identical init: broadcast learner 0's weights
            state = ray_tpu.get(self._workers[0].get_state.remote())
            ray_tpu.get([w.set_state.remote(state)
                         for w in self._workers[1:]])

    @property
    def is_local(self) -> bool:
        return self._local is not None

    def update_from_batch(self, batch: Dict[str, Any]
                          ) -> Dict[str, Dict[str, float]]:
        """One synchronized step: local -> direct; distributed -> shard
        the batch, average gradients (star reduce through the object
        store), apply the same averaged update on every learner."""
        if self._local is not None:
            return self._local.update_from_batch(batch)
        shards = self._shard(batch, len(self._workers))
        # a zero-row shard would produce NaN grads (mean over an empty
        # axis) and poison the average on EVERY replica — small final
        # batches just use fewer learners for the step
        pairs = [(w, s) for w, s in zip(self._workers, shards)
                 if self._shard_rows(s) > 0]
        grad_refs = [w.compute_gradients.remote(s) for w, s in pairs]
        grads = ray_tpu.get(grad_refs)
        avg = {mid: _avg_pytrees([g[mid] for g in grads])
               for mid in grads[0]}
        ray_tpu.get([w.apply_gradients.remote(avg)
                     for w in self._workers])
        return {mid: {"workers": float(len(pairs))} for mid in avg}

    @staticmethod
    def _shard_rows(shard: Dict[str, Any]) -> int:
        first = next(iter(shard.values()))
        if isinstance(first, dict):
            return min((len(next(iter(cols.values())))
                        for cols in shard.values()), default=0)
        return len(first)

    @staticmethod
    def _shard(batch: Dict[str, Any], n: int) -> List[Dict[str, Any]]:
        def split_cols(cols):
            length = len(next(iter(cols.values())))
            cuts = [round(i * length / n) for i in range(n + 1)]
            return [{k: np.asarray(v)[cuts[i]:cuts[i + 1]]
                     for k, v in cols.items()} for i in range(n)]

        first = next(iter(batch.values()))
        if isinstance(first, dict):  # multi-module batch
            per_mid = {mid: split_cols(cols) for mid, cols in batch.items()}
            return [{mid: per_mid[mid][i] for mid in batch}
                    for i in range(n)]
        return split_cols(batch)

    def get_state(self) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.get_state()
        return ray_tpu.get(self._workers[0].get_state.remote())

    def set_state(self, state: Dict[str, Any]):
        if self._local is not None:
            self._local.set_state(state)
            return
        ray_tpu.get([w.set_state.remote(state) for w in self._workers])

    def shutdown(self):
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._workers = []
