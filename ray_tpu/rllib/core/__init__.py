"""ray_tpu.rllib.core — the next-generation RLModule/Learner stack
(reference: rllib/core/)."""

from ray_tpu.rllib.core.learner import (DEFAULT_MODULE_ID, Learner,
                                        PPOLearner)
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import (MultiRLModule, RLModule,
                                          RLModuleSpec)

__all__ = ["RLModule", "RLModuleSpec", "MultiRLModule", "Learner",
           "PPOLearner", "LearnerGroup", "DEFAULT_MODULE_ID"]
