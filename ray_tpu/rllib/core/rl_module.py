"""RLModule — the next-generation model abstraction.

Reference analogue: rllib/core/rl_module/rl_module.py (RLModule:120,
RLModuleSpec) and multi_rl_module.py — the reference's forward-looking
API that separates the NETWORK (RLModule: three forward passes, no
optimizer) from the TRAINING LOOP (Learner: losses + optimizers, see
learner.py).  TPU-first differences by design:

- a module is a flax model + an explicit params pytree; the three
  forwards are jitted batched programs (vector-env-wide, no per-env
  Python), and params stay device pytrees until a weights sync pulls
  them to host numpy;
- specs are plain dataclasses: `build()` is deterministic from
  (spaces, model_config, seed) so learner workers can construct
  identical modules without pickling live modules across processes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.env import Discrete
from ray_tpu.rllib.models import (categorical_entropy, categorical_logp,
                                  categorical_sample,
                                  diag_gaussian_entropy, diag_gaussian_logp,
                                  diag_gaussian_sample, make_model)


class RLModule:
    """Network container with the reference's three forward passes
    (reference: rl_module.py forward_inference:542 /
    forward_exploration:528 / forward_train:556)."""

    def __init__(self, observation_space, action_space,
                 model_config: Optional[Dict[str, Any]] = None,
                 seed: int = 0):
        self.observation_space = observation_space
        self.action_space = action_space
        self.model_config = dict(model_config or {})
        self.discrete = isinstance(action_space, Discrete)
        self.model = make_model(observation_space, action_space,
                                self.model_config or None)
        rng = jax.random.PRNGKey(seed)
        obs_dim = observation_space.shape or (1,)
        dummy = jnp.zeros((1, *obs_dim), jnp.float32)
        self.params = self.model.init(rng, dummy)["params"]
        self._rng = jax.random.fold_in(rng, 1)
        self._jit_inference = jax.jit(self._forward_inference)
        self._jit_exploration = jax.jit(self._forward_exploration)
        self._jit_train = jax.jit(self._forward_train)

    # ---- the three forwards (pure; params passed explicitly so the
    # Learner can differentiate through forward_train) ----

    def _forward_inference(self, params, obs):
        dist_inputs, vf = self.model.apply({"params": params}, obs)
        if self.discrete:
            actions = jnp.argmax(dist_inputs, axis=-1)
        else:
            actions, _ = jnp.split(dist_inputs, 2, axis=-1)
        return {"actions": actions, "action_dist_inputs": dist_inputs,
                "vf_preds": vf}

    def _forward_exploration(self, params, obs, rng):
        dist_inputs, vf = self.model.apply({"params": params}, obs)
        if self.discrete:
            actions = categorical_sample(rng, dist_inputs)
            logp = categorical_logp(dist_inputs, actions)
        else:
            actions = diag_gaussian_sample(rng, dist_inputs)
            logp = diag_gaussian_logp(dist_inputs, actions)
        return {"actions": actions, "action_logp": logp,
                "action_dist_inputs": dist_inputs, "vf_preds": vf}

    def _forward_train(self, params, obs):
        dist_inputs, vf = self.model.apply({"params": params}, obs)
        return {"action_dist_inputs": dist_inputs, "vf_preds": vf}

    # ---- public API (host-facing; reference method names) ----

    def forward_inference(self, batch: Dict[str, np.ndarray]
                          ) -> Dict[str, np.ndarray]:
        out = self._jit_inference(self.params,
                                  jnp.asarray(batch["obs"], jnp.float32))
        return {k: np.asarray(v) for k, v in out.items()}

    def forward_exploration(self, batch: Dict[str, np.ndarray]
                            ) -> Dict[str, np.ndarray]:
        self._rng, sub = jax.random.split(self._rng)
        out = self._jit_exploration(
            self.params, jnp.asarray(batch["obs"], jnp.float32), sub)
        return {k: np.asarray(v) for k, v in out.items()}

    def forward_train(self, batch: Dict[str, np.ndarray]
                      ) -> Dict[str, np.ndarray]:
        out = self._jit_train(self.params,
                              jnp.asarray(batch["obs"], jnp.float32))
        return {k: np.asarray(v) for k, v in out.items()}

    # ---- distribution helpers the Learner's losses use ----

    def logp(self, dist_inputs, actions):
        if self.discrete:
            return categorical_logp(dist_inputs, actions)
        return diag_gaussian_logp(dist_inputs, actions)

    def entropy(self, dist_inputs):
        if self.discrete:
            return categorical_entropy(dist_inputs)
        return diag_gaussian_entropy(dist_inputs)

    # ---- weights ----

    def get_state(self) -> Dict[str, Any]:
        return jax.tree.map(np.asarray, self.params)

    def set_state(self, state: Dict[str, Any]):
        self.params = jax.tree.map(jnp.asarray, state)


@dataclass
class RLModuleSpec:
    """Deterministic module recipe (reference:
    rl_module.py RLModuleSpec) — build() on any worker yields an
    identical module."""

    observation_space: Any = None
    action_space: Any = None
    model_config: Dict[str, Any] = field(default_factory=dict)
    module_class: type = RLModule
    seed: int = 0

    def build(self) -> RLModule:
        return self.module_class(self.observation_space,
                                 self.action_space,
                                 self.model_config, seed=self.seed)


class MultiRLModule:
    """Dict of RLModules by module id (reference:
    multi_rl_module.py MultiRLModule) — the multi-agent container the
    Learner iterates for per-module losses."""

    def __init__(self, specs: Dict[str, RLModuleSpec]):
        self._modules = {mid: spec.build() for mid, spec in specs.items()}

    def __getitem__(self, module_id: str) -> RLModule:
        return self._modules[module_id]

    def __contains__(self, module_id: str) -> bool:
        return module_id in self._modules

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def get_state(self) -> Dict[str, Any]:
        return {mid: m.get_state() for mid, m in self._modules.items()}

    def set_state(self, state: Dict[str, Any]):
        for mid, st in state.items():
            self._modules[mid].set_state(st)
