"""Learner — the next-generation training abstraction.

Reference analogue: rllib/core/learner/learner.py (Learner:139,
compute_loss_for_module, update_from_batch) — the training half of the
RLModule/Learner split: the Learner owns optimizers and losses over one
MultiRLModule; algorithms subclass only `compute_loss_for_module`.

TPU-first: per-module (loss -> grad -> optax update) is ONE jitted
program with donated optimizer state; multi-module updates run each
module's compiled program in sequence (fixed shapes, zero retraces
after warmup).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.core.rl_module import (MultiRLModule, RLModule,
                                          RLModuleSpec)

DEFAULT_MODULE_ID = "default_policy"


class Learner:
    """Owns a MultiRLModule + one optimizer per module; subclasses
    override :meth:`compute_loss_for_module`."""

    def __init__(self, *, module_spec: Optional[RLModuleSpec] = None,
                 module_specs: Optional[Dict[str, RLModuleSpec]] = None,
                 config: Optional[Dict[str, Any]] = None):
        if (module_spec is None) == (module_specs is None):
            raise ValueError(
                "provide exactly one of module_spec / module_specs")
        if module_spec is not None:
            module_specs = {DEFAULT_MODULE_ID: module_spec}
        self.config = dict(config or {})
        self.module = MultiRLModule(module_specs)
        self._opt: Dict[str, Any] = {}
        self._opt_state: Dict[str, Any] = {}
        self._jit_update: Dict[str, Callable] = {}
        self._jit_grads: Dict[str, Callable] = {}
        for mid, mod in self.module.items():
            tx = self.configure_optimizer_for_module(mid)
            self._opt[mid] = tx
            self._opt_state[mid] = tx.init(mod.params)
            self._jit_update[mid] = jax.jit(
                self._make_update(mid), donate_argnums=(0, 1))
            self._jit_grads[mid] = jax.jit(self._make_grads(mid))

    # ---- override points (reference method names) ----

    def configure_optimizer_for_module(self, module_id: str):
        lr = self.config.get("lr", 5e-4)
        clip = self.config.get("grad_clip")
        chain = []
        if clip:
            chain.append(optax.clip_by_global_norm(clip))
        chain.append(optax.adam(lr))
        return optax.chain(*chain)

    def compute_loss_for_module(self, module_id: str, module: RLModule,
                                params, batch: Dict[str, jnp.ndarray]):
        """Return (loss, stats_dict). Differentiated wrt params."""
        raise NotImplementedError

    # ---- update machinery ----

    def _make_update(self, module_id: str):
        module = self.module[module_id]
        tx = self._opt[module_id]

        def _update(params, opt_state, batch):
            def loss_fn(p):
                return self.compute_loss_for_module(
                    module_id, module, p, batch)

            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            stats = dict(stats)
            stats["total_loss"] = loss
            stats["grad_norm"] = optax.global_norm(grads)
            return params, opt_state, stats

        return _update

    def _make_grads(self, module_id: str):
        module = self.module[module_id]

        def _grads(params, batch):
            def loss_fn(p):
                return self.compute_loss_for_module(
                    module_id, module, p, batch)

            (_, _), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return grads

        return _grads

    def _route_batch(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        if any(mid in batch for mid in self.module.keys()):
            return batch
        if DEFAULT_MODULE_ID not in self.module:
            raise ValueError(
                "plain column batch given to a multi-module Learner "
                f"(modules: {sorted(self.module.keys())}); pass "
                "{module_id: batch} so updates route explicitly")
        return {DEFAULT_MODULE_ID: batch}

    def update_from_batch(self, batch: Dict[str, Any]
                          ) -> Dict[str, Dict[str, float]]:
        """One SGD step.  ``batch`` is either a column dict (single
        module) or {module_id: column dict} (reference:
        update_from_batch / MultiAgentBatch routing)."""
        batch = self._route_batch(batch)
        results = {}
        for mid, b in batch.items():
            if mid not in self.module:
                continue
            module = self.module[mid]
            jb = {k: jnp.asarray(v) for k, v in b.items()}
            module.params, self._opt_state[mid], stats = \
                self._jit_update[mid](module.params,
                                      self._opt_state[mid], jb)
            results[mid] = {k: float(v) for k, v in stats.items()
                            if getattr(v, "ndim", 0) == 0}
        return results

    # ---- gradient-exchange hooks for LearnerGroup ----

    def compute_gradients(self, batch: Dict[str, Any]
                          ) -> Dict[str, Any]:
        """Per-module grads as host pytrees (data-parallel learners
        average these; reference: Learner.compute_gradients)."""
        batch = self._route_batch(batch)
        out = {}
        for mid, b in batch.items():
            if mid not in self.module:
                continue
            module = self.module[mid]
            jb = {k: jnp.asarray(v) for k, v in b.items()}
            grads = self._jit_grads[mid](module.params, jb)
            out[mid] = jax.tree.map(np.asarray, grads)
        return out

    def apply_gradients(self, grads: Dict[str, Any]):
        for mid, g in grads.items():
            module = self.module[mid]
            tx = self._opt[mid]
            g = jax.tree.map(jnp.asarray, g)
            updates, self._opt_state[mid] = tx.update(
                g, self._opt_state[mid], module.params)
            module.params = optax.apply_updates(module.params, updates)

    # ---- state ----

    def get_state(self) -> Dict[str, Any]:
        # optimizer state included: a restore that resets Adam moments
        # silently changes learning dynamics (reference Learner
        # persists optimizers too)
        return {"module": self.module.get_state(),
                "optimizer": {mid: jax.tree.map(np.asarray, st)
                              for mid, st in self._opt_state.items()}}

    def set_state(self, state: Dict[str, Any]):
        self.module.set_state(state["module"])
        for mid, st in (state.get("optimizer") or {}).items():
            if mid in self._opt_state:
                self._opt_state[mid] = jax.tree.map(
                    jnp.asarray, st)


class PPOLearner(Learner):
    """Clipped-surrogate PPO loss on the new stack (reference:
    rllib/algorithms/ppo/ppo_learner.py + torch ppo_torch_learner) —
    the canonical example algorithm of the RLModule/Learner API."""

    def compute_loss_for_module(self, module_id, module, params, batch):
        cfg = self.config
        clip = cfg.get("clip_param", 0.2)
        vf_coeff = cfg.get("vf_loss_coeff", 0.5)
        ent_coeff = cfg.get("entropy_coeff", 0.0)
        out = module._forward_train(params, batch["obs"])
        dist_inputs = out["action_dist_inputs"]
        vf = out["vf_preds"]
        logp = module.logp(dist_inputs, batch["actions"])
        ratio = jnp.exp(logp - batch["action_logp"])
        adv = batch["advantages"]
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
        policy_loss = -jnp.mean(surrogate)
        vf_loss = jnp.mean((vf - batch["value_targets"]) ** 2)
        entropy = jnp.mean(module.entropy(dist_inputs))
        loss = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
        return loss, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                      "entropy": entropy}
