"""Offline RL IO: JSON writers/readers of SampleBatches.

Reference analogue: rllib/offline/ (json_writer.py, json_reader.py,
dataset readers). Batches serialize as JSON-lines with base64 numpy
columns, partitioned into rolling files.
"""

from __future__ import annotations

import base64
import glob
import json
import os
from typing import Iterator, List, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


def _encode_col(v: np.ndarray) -> dict:
    v = np.asarray(v)
    return {"dtype": str(v.dtype), "shape": list(v.shape),
            "data": base64.b64encode(v.tobytes()).decode()}


def _decode_col(doc: dict) -> np.ndarray:
    return np.frombuffer(
        base64.b64decode(doc["data"]),
        dtype=np.dtype(doc["dtype"])).reshape(doc["shape"]).copy()


class JsonWriter:
    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_file_size = max_file_size
        self._file = None
        self._file_index = 0

    def _rotate(self):
        if self._file is not None:
            self._file.close()
        self._file_index += 1
        self._file = open(os.path.join(
            self.path, f"output-{self._file_index:05d}.json"), "w")

    def write(self, batch: SampleBatch):
        if self._file is None or \
                self._file.tell() > self.max_file_size:
            self._rotate()
        doc = {k: _encode_col(v) for k, v in batch.items()
               if isinstance(v, np.ndarray) and v.dtype != object}
        self._file.write(json.dumps(doc) + "\n")
        self._file.flush()

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None


class OfflineDataConfigMixin:
    """Fluent ``offline_data(input_path=...)`` config section shared by
    the offline algorithm configs (reference: AlgorithmConfig
    .offline_data())."""

    def offline_data(self, *, input_path=None, **kw):
        if input_path is not None:
            self._config["input_path"] = input_path
        self._config.update(kw)
        return self


class OfflineAlgorithmMixin:
    """Shared offline-dataset plumbing for CQL/CRR (reference:
    offline/json_reader.py usage inside those algorithms): load the
    JsonReader dataset once, rescale env-space actions into the
    policy's (-1, 1) raw space, and draw uniform minibatches."""

    def _load_offline_dataset(self):
        path = self.config.get("input_path")
        if not path:
            raise ValueError(
                f"{type(self).__name__} needs config['input_path']")
        self._data = JsonReader(path).read_all()
        policy = self.workers.local_worker.policy
        if "raw_actions" not in self._data:
            a = np.asarray(self._data[SampleBatch.ACTIONS], np.float32)
            a = a.reshape(a.shape[0], -1)
            span = np.maximum(policy.high - policy.low, 1e-8)
            raw = 2.0 * (a - policy.low) / span - 1.0
            self._data["raw_actions"] = np.clip(raw, -0.999, 0.999)
        self._rng = np.random.default_rng(self.config.get("seed"))

    def _offline_minibatch(self, batch_size: int) -> SampleBatch:
        idx = self._rng.integers(self._data.count, size=batch_size)
        return SampleBatch(
            {k: np.asarray(v)[idx] for k, v in self._data.items()})


class JsonReader:
    def __init__(self, path: str):
        self.files = sorted(glob.glob(os.path.join(path, "*.json"))) \
            if os.path.isdir(path) else [path]
        if not self.files:
            raise ValueError(f"no offline data under {path!r}")

    def read_all(self) -> SampleBatch:
        return SampleBatch.concat_samples(list(self))

    def __iter__(self) -> Iterator[SampleBatch]:
        for f in self.files:
            with open(f) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    doc = json.loads(line)
                    yield SampleBatch(
                        {k: _decode_col(v) for k, v in doc.items()})
