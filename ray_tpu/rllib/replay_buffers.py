"""Replay buffers — uniform ring buffer + prioritized (sum-tree).

Reference analogue: rllib/utils/replay_buffers/ and
rllib/execution/segment_tree.py. Storage is preallocated contiguous numpy
(not per-item pickles) so sampled minibatches are already fixed-shape
columns ready for one `jax.device_put`.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform FIFO replay over preallocated column arrays."""

    def __init__(self, capacity: int = 100_000,
                 seed: Optional[int] = None):
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch):
        n = batch.count
        if not self._cols:
            for k, v in batch.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity, *v.shape[1:]),
                                         v.dtype)
        for k, col in self._cols.items():
            v = np.asarray(batch[k])
            idx = (self._idx + np.arange(n)) % self.capacity
            col[idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, num_items: int) -> SampleBatch:
        idx = self._rng.integers(self._size, size=num_items)
        return SampleBatch({k: col[idx] for k, col in self._cols.items()})


class SumTree:
    """Flat-array segment tree for O(log n) prefix-sum sampling
    (reference: rllib/execution/segment_tree.py)."""

    def __init__(self, capacity: int):
        self.capacity = 1
        while self.capacity < capacity:
            self.capacity *= 2
        self.tree = np.zeros(2 * self.capacity, np.float64)

    def set(self, idx: int, value: float):
        i = idx + self.capacity
        self.tree[i] = value
        i //= 2
        while i >= 1:
            self.tree[i] = self.tree[2 * i] + self.tree[2 * i + 1]
            i //= 2

    def get(self, idx: int) -> float:
        return float(self.tree[idx + self.capacity])

    def total(self) -> float:
        return float(self.tree[1])

    def find_prefixsum_idx(self, prefixsum: float) -> int:
        i = 1
        while i < self.capacity:
            left = 2 * i
            if self.tree[left] > prefixsum:
                i = left
            else:
                prefixsum -= self.tree[left]
                i = left + 1
        return i - self.capacity


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (Schaul et al.) — reference:
    utils/replay_buffers/prioritized_replay_buffer.py."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 seed: Optional[int] = None):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._tree = SumTree(self.capacity)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch):
        n = batch.count
        start = self._idx
        super().add(batch)
        p = self._max_priority ** self.alpha
        for j in range(n):
            self._tree.set((start + j) % self.capacity, p)

    def sample(self, num_items: int, beta: float = 0.4) -> SampleBatch:
        idx = np.empty(num_items, np.int64)
        total = self._tree.total()
        for j in range(num_items):
            mass = self._rng.uniform(0, total)
            i = self._tree.find_prefixsum_idx(mass)
            idx[j] = min(i, self._size - 1)
        probs = np.array([max(self._tree.get(int(i)), 1e-12) for i in idx])
        probs /= max(total, 1e-12)
        weights = (self._size * probs) ** (-beta)
        weights /= weights.max()
        out = SampleBatch({k: col[idx] for k, col in self._cols.items()})
        out["weights"] = weights.astype(np.float32)
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx: np.ndarray, priorities: np.ndarray):
        for i, p in zip(np.asarray(idx), np.asarray(priorities)):
            p = float(abs(p)) + 1e-6
            self._max_priority = max(self._max_priority, p)
            self._tree.set(int(i), p ** self.alpha)
