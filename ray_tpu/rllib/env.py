"""Environment layer: spaces, builtin numpy envs, gymnasium adapter,
and a synchronous VectorEnv.

Reference analogue: rllib/env/ (BaseEnv, vector_env.py, gym wrappers).
TPU-first difference: env stepping always happens on host CPU inside
rollout actors; the vector env presents *stacked numpy* observations so
policies evaluate one batched (jitted) forward per env-step across all
sub-envs instead of per-env Python calls.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class Box:
    def __init__(self, low, high, shape, dtype=np.float32):
        self.low, self.high = low, high
        self.shape = tuple(shape)
        self.dtype = dtype

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        return rng.uniform(self.low, self.high, self.shape).astype(self.dtype)


class Discrete:
    def __init__(self, n: int):
        self.n = int(n)
        self.shape = ()
        self.dtype = np.int32

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        return int(rng.integers(self.n))


class CartPoleEnv:
    """Pure-numpy CartPole-v1 (dynamics per the classic Barto/Sutton/
    Anderson formulation used by gym) — keeps RL tests dependency-free."""

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        config = config or {}
        self.observation_space = Box(-np.inf, np.inf, (4,))
        self.action_space = Discrete(2)
        self._rng = np.random.default_rng(config.get("seed"))
        self._state = None
        self._t = 0

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32), {}

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot ** 2 * sintheta
                ) / total_mass
        thetaacc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costheta ** 2
                           / total_mass))
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * xacc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * thetaacc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._t += 1
        terminated = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT)
        truncated = self._t >= self.MAX_STEPS
        return (self._state.astype(np.float32), 1.0, terminated, truncated,
                {})


class PendulumEnv:
    """Pure-numpy Pendulum-v1 (continuous control smoke env)."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    MAX_STEPS = 200

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        config = config or {}
        self.observation_space = Box(-np.inf, np.inf, (3,))
        self.action_space = Box(-self.MAX_TORQUE, self.MAX_TORQUE, (1,))
        self._rng = np.random.default_rng(config.get("seed"))
        # balance mode: start near upright — the short-credit-horizon
        # variant (swing-up needs long-horizon planning; balancing is
        # the standard quick target for model-based smoke tests)
        self._balance = bool(config.get("balance_init"))
        self._th = self._thdot = 0.0
        self._t = 0

    def _obs(self):
        return np.array([np.cos(self._th), np.sin(self._th), self._thdot],
                        np.float32)

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        if self._balance:
            self._th = self._rng.uniform(-0.3, 0.3)
            self._thdot = self._rng.uniform(-0.2, 0.2)
        else:
            self._th = self._rng.uniform(-np.pi, np.pi)
            self._thdot = self._rng.uniform(-1.0, 1.0)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.MAX_TORQUE, self.MAX_TORQUE))
        th, thdot = self._th, self._thdot
        norm_th = ((th + np.pi) % (2 * np.pi)) - np.pi
        cost = norm_th ** 2 + 0.1 * thdot ** 2 + 0.001 * u ** 2
        thdot = thdot + (3 * self.G / 2 * np.sin(th) + 3.0 * u) * self.DT
        thdot = float(np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED))
        th = th + thdot * self.DT
        self._th, self._thdot = th, thdot
        self._t += 1
        return self._obs(), -cost, False, self._t >= self.MAX_STEPS, {}


class PixelCatcher:
    """Procedurally generated Atari-class pixel env (ALE is not
    installable in this image; reference analogue: the pixel envs the
    reference's release tests run through atari_wrappers.py). An
    84x84x1 uint8 screen: a 4x4 ball falls from a random column; a
    12px paddle at the bottom moves left/stay/right by 6px. +1 for a
    catch, -1 for a miss; episode = ``drops`` balls. Exercises the full
    image path: CNN policy, grayscale/resize/frame-stack connectors."""

    SIZE = 84
    BALL = 4
    PADDLE_W = 12
    PADDLE_H = 4
    STEP_X = 6
    FALL = 6

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        cfg = config or {}
        self.drops = int(cfg.get("drops", 4))
        self.observation_space = Box(0, 255, (self.SIZE, self.SIZE, 1),
                                     np.uint8)
        self.action_space = Discrete(3)
        self._rng = np.random.default_rng(cfg.get("seed"))
        self._ball = [0, 0]
        self._paddle_x = 0
        self._drops_left = 0

    def _spawn(self):
        self._ball = [0, int(self._rng.integers(
            0, self.SIZE - self.BALL))]

    def _obs(self) -> np.ndarray:
        img = np.zeros((self.SIZE, self.SIZE, 1), np.uint8)
        y, x = self._ball
        img[y:y + self.BALL, x:x + self.BALL, 0] = 255
        img[self.SIZE - self.PADDLE_H:,
            self._paddle_x:self._paddle_x + self.PADDLE_W, 0] = 160
        return img

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._paddle_x = (self.SIZE - self.PADDLE_W) // 2
        self._drops_left = self.drops
        self._spawn()
        return self._obs(), {}

    def step(self, action):
        a = int(action)
        self._paddle_x = int(np.clip(
            self._paddle_x + (a - 1) * self.STEP_X,
            0, self.SIZE - self.PADDLE_W))
        self._ball[0] += self.FALL
        reward, term = 0.0, False
        if self._ball[0] + self.BALL >= self.SIZE - self.PADDLE_H:
            bx = self._ball[1]
            caught = (bx + self.BALL > self._paddle_x and
                      bx < self._paddle_x + self.PADDLE_W)
            reward = 1.0 if caught else -1.0
            self._drops_left -= 1
            if self._drops_left <= 0:
                term = True
            else:
                self._spawn()
        return self._obs(), reward, term, False, {}


_BUILTIN_ENVS = {
    "CartPole-v1": CartPoleEnv,
    "Pendulum-v1": PendulumEnv,
    "PixelCatcher-v0": PixelCatcher,
}
# MultiAgentCartPole is appended below (class defined after make_env)


class _GymnasiumAdapter:
    """Wraps a gymnasium env into our 5-tuple step protocol + spaces."""

    def __init__(self, env):
        self._env = env
        self.observation_space = Box(
            getattr(env.observation_space, "low", -np.inf),
            getattr(env.observation_space, "high", np.inf),
            env.observation_space.shape or (),
            env.observation_space.dtype)
        if hasattr(env.action_space, "n"):
            self.action_space = Discrete(env.action_space.n)
        else:
            self.action_space = Box(env.action_space.low,
                                    env.action_space.high,
                                    env.action_space.shape,
                                    env.action_space.dtype)

    def reset(self, *, seed=None):
        return self._env.reset(seed=seed)

    def step(self, action):
        if hasattr(self._env.action_space, "n"):
            action = int(action)
        else:
            action = np.asarray(action, self._env.action_space.dtype).reshape(
                self._env.action_space.shape)
        return self._env.step(action)


def make_env(env_spec: Any, env_config: Optional[Dict[str, Any]] = None):
    """Resolve an env spec: builtin name, gymnasium id, or callable."""
    env_config = env_config or {}
    if callable(env_spec):
        return env_spec(env_config)
    if isinstance(env_spec, str):
        if env_spec in _BUILTIN_ENVS:
            return _BUILTIN_ENVS[env_spec](env_config)
        try:
            import gymnasium
            return _GymnasiumAdapter(gymnasium.make(env_spec, **env_config))
        except Exception as e:
            raise ValueError(f"unknown env {env_spec!r}: {e}") from e
    raise ValueError(f"bad env spec: {env_spec!r}")


class VectorEnv:
    """Synchronous vector of N sub-envs with auto-reset.

    Returns stacked numpy arrays so the policy runs ONE jitted forward for
    all sub-envs per step (reference: rllib/env/vector_env.py, but there
    policies loop per-env in Python far more).
    """

    def __init__(self, env_fn: Callable[[], Any], num_envs: int,
                 seed: Optional[int] = None):
        self.envs = [env_fn() for _ in range(num_envs)]
        self.num_envs = num_envs
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space
        self._seed = seed

    def reset_all(self) -> np.ndarray:
        obs = []
        for i, e in enumerate(self.envs):
            seed = None if self._seed is None else self._seed + i
            o, _ = e.reset(seed=seed)
            obs.append(o)
        return np.stack(obs)

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray,
                        List[dict]]:
        obs, rews, terms, truncs, infos = [], [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, term, trunc, info = e.step(a)
            if term or trunc:
                info = dict(info)
                info["terminal_observation"] = o
                o, _ = e.reset()
            obs.append(o)
            rews.append(r)
            terms.append(term)
            truncs.append(trunc)
            infos.append(info)
        return (np.stack(obs), np.asarray(rews, np.float32),
                np.asarray(terms), np.asarray(truncs), infos)


class MultiAgentEnv:
    """Multi-agent env API (reference: rllib/env/multi_agent_env.py:22).

    reset() -> ({agent_id: obs}, {agent_id: info})
    step({agent_id: action}) -> (obs, rewards, terminateds, truncateds,
    infos) dicts keyed by agent id; terminateds/truncateds carry the
    special "__all__" key ending the episode for everyone.
    """

    agent_ids: List[Any] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[Any, Any]):
        raise NotImplementedError


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPole instances keyed by agent id — the standard
    multi-agent smoke env (reference: rllib/examples/env/
    multi_agent.py MultiAgentCartPole)."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        config = config or {}
        self.num_agents = int(config.get("num_agents", 2))
        self.agent_ids = [f"agent_{i}" for i in range(self.num_agents)]
        self._envs = {aid: CartPoleEnv() for aid in self.agent_ids}
        self._done: Dict[Any, bool] = {}
        e = next(iter(self._envs.values()))
        self.observation_space = e.observation_space
        self.action_space = e.action_space

    def reset(self, *, seed: Optional[int] = None):
        obs, infos = {}, {}
        self._done = {aid: False for aid in self.agent_ids}
        for i, (aid, e) in enumerate(self._envs.items()):
            s = None if seed is None else seed + i
            o, info = e.reset(seed=s)
            obs[aid] = o
            infos[aid] = info
        return obs, infos

    def step(self, action_dict: Dict[Any, Any]):
        obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
        for aid, a in action_dict.items():
            if self._done.get(aid):
                continue
            o, r, term, trunc, info = self._envs[aid].step(a)
            obs[aid], rews[aid] = o, r
            terms[aid], truncs[aid], infos[aid] = term, trunc, info
            if term or trunc:
                self._done[aid] = True
        all_done = all(self._done.values())
        terms["__all__"] = all_done
        truncs["__all__"] = False
        return obs, rews, terms, truncs, infos


_BUILTIN_ENVS["MultiAgentCartPole"] = MultiAgentCartPole
