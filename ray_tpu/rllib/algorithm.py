"""Algorithm + AlgorithmConfig — the RL training driver.

Reference analogue: rllib/algorithms/algorithm.py:142 (step :706,
training_step :1284) and algorithm_config.py (fluent builder). Algorithm
subclasses Tune's Trainable so ``Tuner(PPO, ...)`` works exactly as in the
reference (§3.6 step 1).
"""

from __future__ import annotations

import copy
import time
from typing import Any, Dict, Optional, Type

import numpy as np

from ray_tpu.rllib.rollout_worker import WorkerSet
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent config builder (reference: algorithm_config.py)."""

    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class
        self._config: Dict[str, Any] = {
            "env": None,
            "env_config": {},
            "num_workers": 0,
            "num_envs_per_worker": 1,
            "num_cpus_per_worker": 1,
            "rollout_fragment_length": 200,
            "train_batch_size": 4000,
            "gamma": 0.99,
            "lr": 5e-5,
            "grad_clip": None,
            "seed": 0,
            "explore": True,
            "model": {},
            "min_sample_timesteps_per_iteration": 0,
        }

    # fluent sections, mirroring the reference's grouping
    def environment(self, env=None, *, env_config=None, **kw):
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        self._config.update(kw)
        return self

    def rollouts(self, **kw):
        self._config.update(kw)
        return self

    def training(self, **kw):
        self._config.update(kw)
        return self

    def resources(self, **kw):
        self._config.update(kw)
        return self

    def debugging(self, *, seed=None, **kw):
        if seed is not None:
            self._config["seed"] = seed
        self._config.update(kw)
        return self

    def framework(self, *_a, **_kw):  # always jax here
        return self

    def update_from_dict(self, d: Dict[str, Any]):
        self._config.update(d)
        return self

    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self._config)

    def __getitem__(self, k):
        return self._config[k]

    def get(self, k, default=None):
        return self._config.get(k, default)

    def build(self, env=None) -> "Algorithm":
        if env is not None:
            self._config["env"] = env
        assert self.algo_class is not None, "no algo_class bound"
        return self.algo_class(config=self.to_dict())


class Algorithm(Trainable):
    """Trainable RL algorithm: owns a WorkerSet, steps = sample + learn."""

    _policy_cls = None  # set by subclasses
    _default_config_cls = AlgorithmConfig

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls._default_config_cls(cls)

    def setup(self, config: Dict[str, Any]):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = base
        if self.config.get("env") is None:
            raise ValueError("config['env'] is required")
        self.workers = WorkerSet(self.config, self._policy_cls,
                                 self.config.get("num_workers", 0))
        self._iteration = 0
        self._timesteps_total = 0
        self._episode_reward_window: list = []
        self._t_start = time.time()

    # ---- Trainable API ----

    def step(self) -> Dict[str, Any]:
        t0 = time.time()
        results = self.training_step()
        self._iteration += 1
        metrics = self._collect_rollout_metrics()
        sps = results.get("num_env_steps_sampled_this_iter", 0) / max(
            1e-9, time.time() - t0)
        out = {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "num_env_steps_sampled": self._timesteps_total,
            "env_steps_per_sec": sps,
            "time_total_s": time.time() - self._t_start,
            **metrics,
            **results,
        }
        return out

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def _collect_rollout_metrics(self,
                                 window: int = 100) -> Dict[str, Any]:
        for m in self.workers.collect_metrics():
            self._episode_reward_window.extend(m["episode_rewards"])
        self._episode_reward_window = self._episode_reward_window[-window:]
        rw = self._episode_reward_window
        return {
            "episode_reward_mean": float(np.mean(rw)) if rw else np.nan,
            "episode_reward_max": float(np.max(rw)) if rw else np.nan,
            "episode_reward_min": float(np.min(rw)) if rw else np.nan,
            "episodes_total": len(rw),
        }

    def get_policy(self):
        return self.workers.local_worker.policy

    def compute_single_action(self, obs, explore: bool = False):
        actions, _ = self.get_policy().compute_actions(
            np.asarray(obs)[None], explore=explore)
        return actions[0]

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        """Greedy evaluation rollouts on a fresh env."""
        from ray_tpu.rllib.env import make_env
        env = make_env(self.config["env"], self.config.get("env_config"))
        rewards = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=10_000 + ep)
            total, done = 0.0, False
            while not done:
                a = self.compute_single_action(obs)
                obs, r, term, trunc, _ = env.step(a)
                total += float(r)
                done = term or trunc
            rewards.append(total)
        return {"evaluation": {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_reward_min": float(np.min(rewards)),
            "episode_reward_max": float(np.max(rewards)),
        }}

    # ---- checkpointing (Trainable hooks) ----

    def save_checkpoint(self) -> Dict[str, Any]:
        return {
            "policy_state": self.workers.local_worker.get_policy_state(),
            "iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "config": {k: v for k, v in self.config.items()
                       if not callable(v)},
        }

    def load_checkpoint(self, state: Dict[str, Any]):
        self.workers.local_worker.set_policy_state(state["policy_state"])
        self._iteration = state.get("iteration", 0)
        self._timesteps_total = state.get("timesteps_total", 0)
        self.workers.sync_weights()

    def cleanup(self):
        self.workers.stop()
