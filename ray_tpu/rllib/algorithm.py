"""Algorithm + AlgorithmConfig — the RL training driver.

Reference analogue: rllib/algorithms/algorithm.py:142 (step :706,
training_step :1284) and algorithm_config.py (fluent builder). Algorithm
subclasses Tune's Trainable so ``Tuner(PPO, ...)`` works exactly as in the
reference (§3.6 step 1).
"""

from __future__ import annotations

import copy
import time
from typing import Any, Dict, Optional, Type

import numpy as np

from ray_tpu.rllib.rollout_worker import WorkerSet
from ray_tpu.tune.trainable import Trainable


class AlgorithmConfig:
    """Fluent config builder (reference: algorithm_config.py)."""

    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class
        self._config: Dict[str, Any] = {
            "env": None,
            "env_config": {},
            "num_workers": 0,
            "num_envs_per_worker": 1,
            "num_cpus_per_worker": 1,
            "rollout_fragment_length": 200,
            "train_batch_size": 4000,
            "gamma": 0.99,
            "lr": 5e-5,
            "grad_clip": None,
            "seed": 0,
            "explore": True,
            "model": {},
            "min_sample_timesteps_per_iteration": 0,
            # multi-agent (reference: algorithm_config.py multi_agent())
            "multiagent": {},
            # evaluation workers (reference: .evaluation())
            "evaluation_interval": None,
            "evaluation_num_episodes": 5,
            "evaluation_num_workers": 0,
        }

    # fluent sections, mirroring the reference's grouping
    def environment(self, env=None, *, env_config=None, **kw):
        if env is not None:
            self._config["env"] = env
        if env_config is not None:
            self._config["env_config"] = env_config
        self._config.update(kw)
        return self

    def rollouts(self, **kw):
        self._config.update(kw)
        return self

    def training(self, **kw):
        self._config.update(kw)
        return self

    def resources(self, **kw):
        self._config.update(kw)
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None,
                    policies_to_train=None, **kw):
        ma = self._config.setdefault("multiagent", {})
        if policies is not None:
            ma["policies"] = policies
        if policy_mapping_fn is not None:
            ma["policy_mapping_fn"] = policy_mapping_fn
        if policies_to_train is not None:
            ma["policies_to_train"] = policies_to_train
        ma.update(kw)
        return self

    def evaluation(self, **kw):
        self._config.update(kw)
        return self

    def debugging(self, *, seed=None, **kw):
        if seed is not None:
            self._config["seed"] = seed
        self._config.update(kw)
        return self

    def framework(self, *_a, **_kw):  # always jax here
        return self

    def update_from_dict(self, d: Dict[str, Any]):
        self._config.update(d)
        return self

    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self._config)

    def __getitem__(self, k):
        return self._config[k]

    def get(self, k, default=None):
        return self._config.get(k, default)

    def build(self, env=None) -> "Algorithm":
        if env is not None:
            self._config["env"] = env
        assert self.algo_class is not None, "no algo_class bound"
        return self.algo_class(config=self.to_dict())


class Algorithm(Trainable):
    """Trainable RL algorithm: owns a WorkerSet, steps = sample + learn."""

    _policy_cls = None  # set by subclasses
    _default_config_cls = AlgorithmConfig

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls._default_config_cls(cls)

    def setup(self, config: Dict[str, Any]):
        base = self.get_default_config().to_dict()
        base.update(config or {})
        self.config = base
        if self.config.get("env") is None:
            raise ValueError("config['env'] is required")
        self.workers = WorkerSet(self.config, self._policy_cls,
                                 self.config.get("num_workers", 0))
        # evaluation WorkerSet: greedy policies, fresh envs (reference:
        # algorithm.py evaluation_workers + evaluation_config overrides)
        self.evaluation_workers = None
        if self.config.get("evaluation_interval"):
            n_eval = self.config.get("evaluation_num_workers", 0)
            if n_eval > 0:
                eval_cfg = dict(self.config)
                eval_cfg["explore"] = False
                eval_cfg["evaluation_interval"] = None
                self.evaluation_workers = WorkerSet(
                    eval_cfg, self._policy_cls, n_eval)
        self._iteration = 0
        self._timesteps_total = 0
        self._episode_reward_window: list = []
        self._t_start = time.time()

    # ---- Trainable API ----

    def step(self) -> Dict[str, Any]:
        t0 = time.time()
        results = self.training_step()
        self._iteration += 1
        metrics = self._collect_rollout_metrics()
        sps = results.get("num_env_steps_sampled_this_iter", 0) / max(
            1e-9, time.time() - t0)
        out = {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "num_env_steps_sampled": self._timesteps_total,
            "env_steps_per_sec": sps,
            "time_total_s": time.time() - self._t_start,
            **metrics,
            **results,
        }
        interval = self.config.get("evaluation_interval")
        if interval and self._iteration % interval == 0:
            out.update(self._run_evaluation())
        return out

    def _run_evaluation(self) -> Dict[str, Any]:
        n_eps = self.config.get("evaluation_num_episodes", 5)
        if self.evaluation_workers is None:
            return self.evaluate(num_episodes=n_eps)
        import ray_tpu
        # current learner weights (and connector stats — normalization
        # must match training) onto the greedy eval policies
        lw = self.workers.local_worker
        ref = ray_tpu.put(lw.get_weights())
        eval_workers = self.evaluation_workers.remote_workers
        ray_tpu.get([w.set_weights.remote(ref) for w in eval_workers])
        if hasattr(lw, "get_connector_state"):
            cs = lw.get_connector_state()
            if any(cs.values()):
                ray_tpu.get([w.set_connector_state.remote(cs)
                             for w in eval_workers])
        per = max(1, n_eps // len(eval_workers))
        rewards: list = []
        for rw in ray_tpu.get([w.evaluate_episodes.remote(per)
                               for w in eval_workers]):
            rewards.extend(rw)
        return {"evaluation": {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_reward_min": float(np.min(rewards)),
            "episode_reward_max": float(np.max(rewards)),
            "episodes_this_eval": len(rewards),
        }}

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def _collect_rollout_metrics(self,
                                 window: int = 100) -> Dict[str, Any]:
        for m in self.workers.collect_metrics():
            self._episode_reward_window.extend(m["episode_rewards"])
        self._episode_reward_window = self._episode_reward_window[-window:]
        rw = self._episode_reward_window
        return {
            "episode_reward_mean": float(np.mean(rw)) if rw else np.nan,
            "episode_reward_max": float(np.max(rw)) if rw else np.nan,
            "episode_reward_min": float(np.min(rw)) if rw else np.nan,
            "episodes_total": len(rw),
        }

    def get_policy(self, policy_id: Optional[str] = None):
        lw = self.workers.local_worker
        if policy_id is not None:
            return lw.policy_map[policy_id]
        return lw.policy

    def compute_single_action(self, obs, explore: bool = False,
                              policy_id: Optional[str] = None):
        lw = self.workers.local_worker
        obs = np.asarray(obs)[None]
        conns = getattr(lw, "obs_connectors", None)
        if conns is not None and conns.connectors:
            # inference must see the same preprocessing as training
            obs = conns.transform(obs)
        actions, _ = self.get_policy(policy_id).compute_actions(
            obs, explore=explore)
        act_conns = getattr(lw, "action_connectors", None)
        if act_conns is not None and act_conns.connectors:
            actions = act_conns.transform(actions)
        return actions[0]

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        """Greedy evaluation rollouts — delegates to the local worker's
        evaluate_episodes so single/multi-agent and connector handling
        live in ONE place (rollout_worker.py)."""
        rewards = self.workers.local_worker.evaluate_episodes(num_episodes)
        return {"evaluation": {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_reward_min": float(np.min(rewards)),
            "episode_reward_max": float(np.max(rewards)),
        }}

    # ---- checkpointing (Trainable hooks) ----

    @staticmethod
    def _pickle_safe(v):
        """Drop callables at ANY depth (policy_mapping_fn lambdas inside
        config['multiagent'], connector instances, env builders) so the
        checkpoint always pickles."""
        if callable(v):
            return None
        if isinstance(v, dict):
            return {k: Algorithm._pickle_safe(x) for k, x in v.items()
                    if not callable(x)}
        if isinstance(v, (list, tuple)):
            return type(v)(Algorithm._pickle_safe(x) for x in v
                           if not callable(x))
        return v

    def save_checkpoint(self) -> Dict[str, Any]:
        lw = self.workers.local_worker
        return {
            "policy_state": lw.get_policy_state(),
            "connector_state": (lw.get_connector_state()
                                if hasattr(lw, "get_connector_state")
                                else None),
            "iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
            "config": self._pickle_safe(self.config),
        }

    def load_checkpoint(self, state: Dict[str, Any]):
        lw = self.workers.local_worker
        lw.set_policy_state(state["policy_state"])
        if state.get("connector_state") and \
                hasattr(lw, "set_connector_state"):
            lw.set_connector_state(state["connector_state"])
        self._iteration = state.get("iteration", 0)
        self._timesteps_total = state.get("timesteps_total", 0)
        self.workers.sync_weights()

    def cleanup(self):
        if self.evaluation_workers is not None:
            self.evaluation_workers.stop()
        self.workers.stop()


class LocalAlgorithm(Algorithm):
    """Base for algorithms that own their env loop instead of sampling
    through a WorkerSet — QMIX's joint-transition collector, R2D2's
    recurrent-state collector. Provides the shared driver plumbing:
    counters, the epsilon schedule, periodic hard target sync, local
    episode metrics, and params/target/opt checkpointing. Subclasses
    set ``self.params/self.target_params/self.opt_state`` in setup()."""

    def _init_local_state(self):
        import jax
        import numpy as _np
        self.evaluation_workers = None  # Algorithm.step expects the attr
        self._np_rng = _np.random.default_rng(self.config.get("seed"))
        self._iteration = 0
        self._timesteps_total = 0
        self._steps_since_target_sync = 0
        self._episode_reward_window: list = []
        self._t_start = time.time()

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._timesteps_total
                   / max(1, cfg["epsilon_timesteps"]))
        return cfg["initial_epsilon"] + frac * (
            cfg["final_epsilon"] - cfg["initial_epsilon"])

    def _maybe_sync_target(self, steps: int):
        import jax
        import jax.numpy as jnp
        self._steps_since_target_sync += steps
        if (self._steps_since_target_sync
                >= self.config["target_network_update_freq"]):
            self.target_params = jax.tree_util.tree_map(
                jnp.copy, self.params)
            self._steps_since_target_sync = 0

    def _collect_rollout_metrics(self, window: int = 100):
        self._episode_reward_window = \
            self._episode_reward_window[-window:]
        rw = self._episode_reward_window
        return {
            "episode_reward_mean": float(np.mean(rw)) if rw else np.nan,
            "episode_reward_max": float(np.max(rw)) if rw else np.nan,
            "episode_reward_min": float(np.min(rw)) if rw else np.nan,
            "episodes_total": len(rw),
        }

    def _collect_joint(self, act_fn, num_steps: int) -> int:
        """Joint-transition collector shared by the cooperative
        multi-agent algorithms (QMIX, MADDPG). ``act_fn(obs_dict)``
        returns (env_action_dict, stored_action_array (n, ...)); rows
        carry the TEAM reward (mean over agents), terminal-only dones
        (TD bootstraps through time-limit truncation), and stacked
        per-agent obs. Appends one SampleBatch to ``self.replay``."""
        rows: Dict[str, list] = {k: [] for k in
                                 ("obs", "actions", "rewards", "dones",
                                  "next_obs")}
        for _ in range(num_steps):
            acts, stored = act_fn(self._obs)
            nobs, rews, terms, truncs, _ = self.env.step(acts)
            terminal = bool(terms.get("__all__"))
            done = terminal or bool(truncs.get("__all__"))
            team_r = float(np.mean([rews[a] for a in self.agent_ids]))
            rows["obs"].append(
                np.stack([self._obs[a] for a in self.agent_ids]))
            rows["actions"].append(stored)
            rows["rewards"].append(np.float32(team_r))
            rows["dones"].append(terminal)
            # on terminal, next obs may be missing for done agents:
            # fall back to the last obs (masked out by dones in the TD)
            rows["next_obs"].append(np.stack(
                [nobs.get(a, self._obs[a]) for a in self.agent_ids]))
            self._episode_reward += team_r
            if done:
                self._episode_reward_window.append(self._episode_reward)
                self._episode_reward = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nobs
        from ray_tpu.rllib.sample_batch import SampleBatch
        self.replay.add(SampleBatch(
            {k: np.stack(v) if np.asarray(v[0]).ndim
             else np.asarray(v) for k, v in rows.items()}))
        return num_steps

    def _eval_episodes(self, act_fn, num_episodes: int,
                       seed_base: int = 10_000,
                       on_reset=None) -> Dict[str, Any]:
        """Greedy evaluation loop shared by the self-contained
        algorithms. ``act_fn(obs)`` returns an action (or a joint
        action dict for a MultiAgentEnv); ``on_reset()`` clears
        per-episode acting state (LSTM carry, DT context window)."""
        from ray_tpu.rllib.env import MultiAgentEnv
        multi = isinstance(self.env, MultiAgentEnv)
        rewards = []
        for ep in range(num_episodes):
            obs, _ = self.env.reset(seed=seed_base + ep)
            if on_reset is not None:
                on_reset()
            total, done = 0.0, False
            while not done:
                obs, rews, terms, truncs, _ = self.env.step(act_fn(obs))
                if multi:
                    total += float(np.mean(list(rews.values())))
                    done = bool(terms.get("__all__")
                                or truncs.get("__all__"))
                else:
                    total += float(rews)
                    done = bool(terms or truncs)
            rewards.append(total)
        return {"evaluation": {
            "episode_reward_mean": float(np.mean(rewards)),
            "episode_reward_min": float(np.min(rewards)),
            "episode_reward_max": float(np.max(rewards)),
        }}

    def save_checkpoint(self) -> Dict[str, Any]:
        import jax
        return {
            "params": jax.device_get(self.params),
            "target_params": jax.device_get(self.target_params),
            "opt_state": jax.device_get(self.opt_state),
            "iteration": self._iteration,
            "timesteps_total": self._timesteps_total,
        }

    def load_checkpoint(self, state: Dict[str, Any]):
        import jax
        import jax.numpy as jnp

        def as_jnp(t):
            return jax.tree_util.tree_map(
                jnp.asarray, t,
                is_leaf=lambda x: isinstance(x, (np.ndarray,
                                                 np.generic)))

        self.params = as_jnp(state["params"])
        self.target_params = as_jnp(state["target_params"])
        self.opt_state = as_jnp(state["opt_state"])
        self._iteration = state.get("iteration", 0)
        self._timesteps_total = state.get("timesteps_total", 0)

    def cleanup(self):
        pass  # no worker actors to stop
