"""Model catalog: flax policy/value networks.

Reference analogue: rllib/models/catalog.py + models/torch/ — but built as
flax modules whose forward is shape-static and jit/pjit-friendly. Conv
stacks use NHWC (TPU-native layout) and compute in bfloat16 with float32
heads where it matters.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.env import Box, Discrete


class MLPNet(nn.Module):
    """MLP with policy logits + value heads
    (reference: rllib/models/torch/fcnet.py). Value branch is a separate
    trunk by default (the reference's PPO `vf_share_layers=False`) so the
    large-magnitude value loss can't wreck the policy features."""

    num_outputs: int
    hiddens: Sequence[int] = (256, 256)
    activation: str = "tanh"
    free_log_std: bool = False  # continuous: state-independent log-std
    vf_share_layers: bool = False

    @nn.compact
    def __call__(self, obs):
        act = {"tanh": nn.tanh, "relu": nn.relu, "swish": nn.swish}[
            self.activation]
        x = obs.astype(jnp.float32)
        x = x.reshape((x.shape[0], -1))

        def trunk(inp, name):
            h_out = inp
            for i, h in enumerate(self.hiddens):
                h_out = act(nn.Dense(
                    h, kernel_init=nn.initializers.orthogonal(np.sqrt(2)),
                    name=f"{name}_{i}")(h_out))
            return h_out

        pi = trunk(x, "pi")
        vf = pi if self.vf_share_layers else trunk(x, "vf")
        logits = nn.Dense(self.num_outputs,
                          kernel_init=nn.initializers.orthogonal(0.01))(pi)
        value = nn.Dense(1, kernel_init=nn.initializers.orthogonal(1.0))(vf)
        if self.free_log_std:
            log_std = self.param("log_std", nn.initializers.zeros,
                                 (self.num_outputs,))
            logits = jnp.concatenate(
                [logits, jnp.broadcast_to(log_std, logits.shape)], axis=-1)
        return logits, value[..., 0]


class AtariCNN(nn.Module):
    """Nature-DQN conv trunk in NHWC/bfloat16 for the MXU
    (reference: rllib/models/torch/visionnet.py)."""

    num_outputs: int
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(self.compute_dtype) / 255.0
        for feat, kern, stride in ((32, 8, 4), (64, 4, 2), (64, 3, 1)):
            x = nn.relu(nn.Conv(feat, (kern, kern), strides=(stride, stride),
                                dtype=self.compute_dtype)(x))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(512, dtype=self.compute_dtype)(x))
        x = x.astype(jnp.float32)
        logits = nn.Dense(self.num_outputs)(x)
        value = nn.Dense(1)(x)
        return logits, value[..., 0]


def num_action_outputs(action_space) -> Tuple[int, bool]:
    """(num distribution inputs before log-std doubling, is_discrete)."""
    if isinstance(action_space, Discrete):
        return action_space.n, True
    return int(np.prod(action_space.shape)), False


def make_model(obs_space, action_space,
               model_config: Optional[Dict[str, Any]] = None) -> nn.Module:
    """Pick a network for the given spaces (reference:
    models/catalog.py ModelCatalog.get_model_v2)."""
    model_config = model_config or {}
    n_out, discrete = num_action_outputs(action_space)
    if len(obs_space.shape) == 3:
        return AtariCNN(num_outputs=n_out)
    return MLPNet(
        num_outputs=n_out,
        hiddens=tuple(model_config.get("fcnet_hiddens", (256, 256))),
        activation=model_config.get("fcnet_activation", "tanh"),
        free_log_std=not discrete,
        vf_share_layers=model_config.get("vf_share_layers", False))


# ---- action distributions (functional, jit-safe) ----


def categorical_sample(rng, logits):
    return jax.random.categorical(rng, logits, axis=-1)


def categorical_logp(logits, actions):
    logp = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(
        logp, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]


def categorical_entropy(logits):
    logp = jax.nn.log_softmax(logits)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def diag_gaussian_split(dist_inputs):
    mean, log_std = jnp.split(dist_inputs, 2, axis=-1)
    return mean, jnp.clip(log_std, -20.0, 2.0)


def diag_gaussian_sample(rng, dist_inputs):
    mean, log_std = diag_gaussian_split(dist_inputs)
    return mean + jnp.exp(log_std) * jax.random.normal(rng, mean.shape)


def diag_gaussian_logp(dist_inputs, actions):
    mean, log_std = diag_gaussian_split(dist_inputs)
    actions = actions.reshape(mean.shape)
    var = jnp.exp(2 * log_std)
    return jnp.sum(
        -0.5 * ((actions - mean) ** 2 / var)
        - log_std - 0.5 * jnp.log(2 * jnp.pi), axis=-1)


def diag_gaussian_entropy(dist_inputs):
    _, log_std = diag_gaussian_split(dist_inputs)
    return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)
