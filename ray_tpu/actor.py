"""Actor API: ``@ray_tpu.remote`` classes, handles, and the actor transport.

Role-equivalent to the reference's python/ray/actor.py (ActorClass._remote
:657, ActorMethod._remote :161) over the direct actor transport
(core_worker/transport/direct_actor_task_submitter.cc): after creation, method
calls go *directly* to the actor's worker process over a peer connection with
no raylet involvement; the GCS only mediates creation, restarts, and naming
(gcs_actor_manager.cc semantics).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ray_tpu._private import netx, serialization, worker as worker_mod
from ray_tpu._private.worker import (ObjectRef, PendingTaskState,
                                     global_worker)
from ray_tpu.common.ids import ActorID, ObjectID, TaskID
from ray_tpu.common.options import (resource_dict_from_options,
                                    validate_options)
from ray_tpu import exceptions as exc


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._name = name
        self._options = options or {}

    def remote(self, *args, **kwargs) -> ObjectRef:
        return self._handle._remote_call(self._name, args, kwargs,
                                         self._options)

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, opts)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor methods cannot be called directly; use "
            f".{self._name}.remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str,
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._max_task_retries = max_task_retries
        self._worker_address: Optional[str] = None
        # picked direct-lane endpoint (unix same-host, host:port off-box);
        # "" = none advertised — calls then ride the asyncio peer path
        self._direct_addr: str = ""
        self._seq = 0
        self._lock = threading.Lock()
        self._dead_reason: Optional[str] = None

    @property
    def _id_hex(self) -> str:
        return self._actor_id.hex()

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._max_task_retries))

    # ------------------------------------------------------------------ calls

    def _resolve_address(self, timeout: float = 120.0) -> str:
        w = global_worker()
        if self._worker_address:
            return self._worker_address
        info = w.call_sync(w.gcs, "wait_actor_alive",
                           {"actor_id": self._id_hex, "timeout": timeout},
                           timeout=timeout + 5)
        if info.get("error"):
            raise exc.ActorDiedError(self._id_hex, info["error"])
        if info["state"] == "DEAD":
            raise exc.ActorDiedError(self._id_hex,
                                     info.get("death_cause") or "dead")
        self._worker_address = info["worker_address"]
        self._direct_addr = netx.pick(info.get("direct_address"),
                                      info.get("direct_tcp_address"))
        return self._worker_address

    def _remote_call(self, method: str, args, kwargs,
                     opts: Dict[str, Any]) -> ObjectRef:
        w = global_worker()
        task_id = TaskID.for_task(w.current_task_id
                                  or TaskID.for_driver(w.job_id))
        # _serialize_args (not bare serialize): promotes large numpy args
        # to plasma AND pins contained refs via add_submitted — without the
        # pin, a temporary like m.remote(put(x)) lets the driver free the
        # arg object while the call is in flight, and the actor's arg
        # resolution waits forever on an object that will never reappear
        # (the un-pinned path wedged every Ape-X/IMPALA weight broadcast)
        arg_blob, _plasma_deps, arg_refs = w._serialize_args(
            list(args), kwargs)
        payload = {
            "task_id": task_id.hex(),
            "method": method,
            "args": arg_blob,
            # "seq"/"processed_up_to" are stamped at enqueue time below
            "caller": w.address,
            # span propagation (1.6): the executing actor adopts this
            # ctx so tasks it submits parent under the call, not under
            # the actor worker's own root trace
            "trace_ctx": w._trace_ctx_for_submit(),
        }
        oid = ObjectID.for_return(task_id, 0)
        state = PendingTaskState({"task_id": task_id.hex(),
                                  "fn_name": f"{self._class_name}.{method}",
                                  "arg_refs": arg_refs},
                                 self._max_task_retries, [oid])
        w.pending_tasks[task_id.hex()] = state
        w.reference_counter.add_owned(oid)

        async def _call(attempt: int = 0):
            try:
                await _call_inner(attempt)
            except BaseException as e:  # noqa: BLE001 — last resort
                # a send task dying WITHOUT storing a result strands the
                # caller forever (observed rarely under load); convert
                # any leak through the structured paths below into a
                # visible, retryable error instead
                if not state.done:
                    _store_actor_error(w, state, exc.ActorUnavailableError(
                        f"actor call send task failed: "
                        f"{type(e).__name__}: {e}"))
                    w.mark_actor_seq_done(self._id_hex, payload["seq"])

        async def _call_inner(attempt: int = 0):
            try:
                # cached-address fast path: no executor hop, so the task
                # body runs straight through conn.call's synchronous
                # write — event-loop start order (= seq order, see the
                # enqueue below) is then the wire order, and receiver-
                # side parking stays a cold-start/retry backstop instead
                # of a steady-state cost
                addr = self._worker_address
                if addr is None:
                    addr = await _to_thread(self._resolve_address)
                # retries skip the direct lane: a severed TCP direction
                # (net.partition) must not pin every retry to the dead
                # fast path while the worker's own socket still answers
                direct = self._direct_addr if attempt == 0 else ""
                nx = netx.get_client() if direct else None
                if nx is not None:
                    # direct lane (1.8): frame goes out inside call_async
                    # itself, so event-loop start order is still the wire
                    # order; failures surface as ConnectionError and take
                    # the same restart/retry path as a dropped peer conn
                    ret = await nx.call_async(direct, "actor_call", payload)
                else:
                    conn = await w._peer(addr)
                    ret = await conn.call("actor_call", payload)
                _store_actor_result(w, state, ret)
                w.mark_actor_seq_done(self._id_hex, payload["seq"])
            except exc.ActorDiedError as e:
                _store_actor_error(w, state, e)
                w.mark_actor_seq_done(self._id_hex, payload["seq"])
            except Exception as e:  # connection error → maybe restart
                self._worker_address = None
                self._direct_addr = ""
                info = None
                try:
                    info = await w.gcs.call(
                        "get_actor", {"actor_id": self._id_hex})
                except Exception:
                    pass
                restartable = info and info.get("state") in (
                    "RESTARTING", "PENDING_CREATION", "ALIVE")
                if restartable and (self._max_task_retries == -1
                                    or attempt < max(self._max_task_retries, 0)):
                    await _to_thread(time.sleep, 0.2)
                    await _call_inner(attempt + 1)
                elif restartable and self._max_task_retries == 0:
                    _store_actor_error(
                        w, state, exc.ActorUnavailableError(
                            f"actor {self._id_hex[:8]} restarting; call not "
                            f"retried (max_task_retries=0): {e}"))
                    w.mark_actor_seq_done(self._id_hex, payload["seq"])
                else:
                    reason = (info or {}).get("death_cause") or str(e)
                    _store_actor_error(
                        w, state, exc.ActorDiedError(self._id_hex, reason))
                    w.mark_actor_seq_done(self._id_hex, payload["seq"])

        # seq allocation and event-loop enqueue are ATOMIC: sequence
        # numbers are per (process, actor) in caller program order, and
        # run_coroutine_threadsafe preserves enqueue order, so with the
        # fast path above the frames leave in seq order (reference:
        # actor_scheduling_queue.cc per-caller ordering; the receiver
        # parks out-of-order arrivals as the backstop)
        seq = w.enqueue_actor_call(self._id_hex, payload, _call)
        return ObjectRef(oid, w.address)


async def _to_thread(fn, *args):
    import asyncio
    return await asyncio.get_running_loop().run_in_executor(None, fn, *args)


def _release_submitted_args(w, state: PendingTaskState):
    for hex_ref, _owner in state.spec.get("arg_refs", []):
        w.reference_counter.remove_submitted(ObjectID.from_hex(hex_ref))
    state.spec["arg_refs"] = []


def _store_actor_result(w, state: PendingTaskState, ret: Dict[str, Any]):
    _release_submitted_args(w, state)
    oid = ObjectID.from_hex(ret["object_id"])
    target = state.return_ids[0]
    if ret.get("inline") is not None:
        w.memory_store.put(target, ret["inline"])
    else:
        ind = worker_mod._PlasmaIndirect(ret.get("node_id", ""))
        # the actor shipped the value under its own oid; alias it
        if oid != target:
            w.memory_store.put(target,
                               serialization.serialize(ind).to_bytes())
        else:
            w.memory_store.put(target,
                               serialization.serialize(ind).to_bytes())
    state.done = True
    state.result_event.set()


def _store_actor_error(w, state: PendingTaskState, e: Exception):
    _release_submitted_args(w, state)
    payload = serialization.serialize_error(e).to_bytes()
    for oid in state.return_ids:
        w.memory_store.put(oid, payload)
    state.done = True
    state.result_event.set()


def _normalize_concurrency_groups(groups) -> Dict[str, int]:
    """Accept {name: n} or the reference's [{"name":..,
    "max_concurrency":..}] list form (actor concurrency groups)."""
    if not groups:
        return {}
    if isinstance(groups, dict):
        return {str(k): int(v) for k, v in groups.items()}
    out = {}
    for g in groups:
        out[str(g["name"])] = int(g.get("max_concurrency", 1))
    return out


class ActorClass:
    """Result of decorating a class with ``@ray_tpu.remote``."""

    def __init__(self, cls, default_opts: Dict[str, Any]):
        self._cls = cls
        self._default_opts = validate_options(default_opts, is_actor=True)
        self._class_key: Optional[str] = None
        self._class_key_mgr = None

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actors must be created with {self._cls.__name__}.remote()")

    def options(self, **opts) -> "_BoundActorClass":
        merged = {**self._default_opts, **validate_options(opts, is_actor=True)}
        return _BoundActorClass(self, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._create(self._default_opts, args, kwargs)

    def bind(self, *args, **kwargs):
        """DAG authoring (reference: python/ray/dag ClassNode)."""
        from ray_tpu.dag import ClassNode
        return ClassNode(self, args, kwargs, self._default_opts)

    def _create(self, opts: Dict[str, Any], args, kwargs) -> ActorHandle:
        from ray_tpu.util.client.worker import client_mode
        c = client_mode()
        if c is not None and c.connected:
            return c.create_actor(self._cls, args, kwargs, opts)
        w = global_worker()
        if self._class_key is None or \
                self._class_key_mgr is not w.function_manager:
            self._class_key = w.function_manager.export(self._cls, kind="cls")
            self._class_key_mgr = w.function_manager
        actor_id = ActorID.of(w.job_id)
        # same pinning as method calls: Actor.remote(put(x)) must keep x
        # alive until the constructor has run. Released once the actor
        # settles (ALIVE or DEAD) — note a later RESTART re-running the
        # constructor after release relies on lineage reconstruction.
        arg_blob, _deps, arg_refs = w._serialize_args(list(args), kwargs)

        def _release_ctor_args():
            if not arg_refs:
                return

            async def _go():
                try:
                    await w.gcs.call(
                        "wait_actor_alive",
                        {"actor_id": actor_id.hex(), "timeout": 600},
                        timeout=610)
                except Exception:
                    pass
                for hex_ref, _owner in arg_refs:
                    w.reference_counter.remove_submitted(
                        ObjectID.from_hex(hex_ref))
            try:
                w.io.run_async(_go())
            except Exception:
                pass

        resources = resource_dict_from_options(opts, is_actor=True)
        sched = w._scheduling_from_opts(opts)
        pg = w._pg_from_opts(opts)
        create_spec = {
            "actor_id": actor_id.hex(),
            "class_key": self._class_key,
            "class_name": self._cls.__name__,
            "init_args": arg_blob,
            "max_concurrency": opts.get("max_concurrency", 1),
            "concurrency_groups": _normalize_concurrency_groups(
                opts.get("concurrency_groups")),
            "runtime_env": w.prepare_runtime_env(opts.get("runtime_env")),
            "placement_group": pg,
            "job_id": w.job_id.hex(),
        }
        reg = w.call_sync(w.gcs, "register_actor", {
            "actor_id": actor_id.hex(),
            "name": opts.get("name"),
            "namespace": opts.get("namespace", w.namespace),
            "class_name": self._cls.__name__,
            "owner_address": w.address,
            "detached": opts.get("lifetime") == "detached",
            "resources": resources,
            "max_restarts": opts.get(
                "max_restarts", w.config.actor_max_restarts_default),
            "scheduling": sched,
            "get_if_exists": opts.get("get_if_exists", False),
            "create_spec": create_spec,
        })
        if reg.get("error") or reg.get("existing"):
            # no creation will run: drop the constructor-arg pins now
            for hex_ref, _owner in arg_refs:
                w.reference_counter.remove_submitted(
                    ObjectID.from_hex(hex_ref))
            if reg.get("error"):
                raise ValueError(reg["error"])
            return get_actor_by_id(reg["actor_id"])
        try:
            w.call_sync(w.gcs, "create_actor", {
                "actor_id": actor_id.hex(), "create_spec": create_spec})
        except BaseException:
            for hex_ref, _owner in arg_refs:
                w.reference_counter.remove_submitted(
                    ObjectID.from_hex(hex_ref))
            raise
        _release_ctor_args()
        return ActorHandle(actor_id, self._cls.__name__,
                           opts.get("max_task_retries", 0))


class _BoundActorClass:
    def __init__(self, actor_class: ActorClass, opts: Dict[str, Any]):
        self._actor_class = actor_class
        self._opts = opts

    def remote(self, *args, **kwargs) -> ActorHandle:
        return self._actor_class._create(self._opts, args, kwargs)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode
        return ClassNode(self._actor_class, args, kwargs, self._opts)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    from ray_tpu.util.client.worker import client_mode
    c = client_mode()
    if c is not None and c.connected:
        return c.get_named_actor(name, namespace)
    w = global_worker()
    info = w.call_sync(w.gcs, "get_named_actor", {
        "name": name, "namespace": namespace if namespace is not None
        else w.namespace})
    if info.get("error"):
        raise ValueError(info["error"])
    handle = ActorHandle(ActorID.from_hex(info["actor_id"]),
                         info.get("class_name", ""))
    if info.get("worker_address"):
        handle._worker_address = info["worker_address"]
        handle._direct_addr = netx.pick(info.get("direct_address"),
                                        info.get("direct_tcp_address"))
    return handle


def get_actor_by_id(actor_id_hex: str) -> ActorHandle:
    w = global_worker()
    info = w.call_sync(w.gcs, "get_actor", {"actor_id": actor_id_hex})
    if info.get("error"):
        raise ValueError(info["error"])
    handle = ActorHandle(ActorID.from_hex(actor_id_hex),
                         info.get("class_name", ""))
    if info.get("worker_address"):
        handle._worker_address = info["worker_address"]
        handle._direct_addr = netx.pick(info.get("direct_address"),
                                        info.get("direct_tcp_address"))
    return handle


def kill(actor: ActorHandle, *, no_restart: bool = True):
    w = global_worker()
    w.call_sync(w.gcs, "kill_actor", {"actor_id": actor._id_hex,
                                      "no_restart": no_restart})
