"""Flash attention for TPU (Pallas) with an XLA fallback.

No reference analogue — the reference delegates all kernel work to
torch/CUDA (SURVEY.md §2.6: TP/SP absent, math lives inside train_func).
For a TPU-native framework the fused attention kernel is a core op: it keeps
the S×S score matrix out of HBM (block-online softmax in VMEM), which is what
makes long-context training possible at all.

Algorithm: standard flash attention v2 tiling.
  forward: for each q block, stream kv blocks; online softmax keeps running
  max m and normalizer l; out = acc / l; LSE saved for backward.
  backward: two kernels — dkv (grid over kv blocks, loop q) and dq (grid over
  q blocks, loop kv) — recompute p from saved LSE.

Shapes: [batch, heads, seq, head_dim]; block sizes default 128 (MXU tile).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_Q = 512   # measured on v5e: 512 halves per-program overhead
DEFAULT_BLOCK_K = 512   # vs 128 at s=1024 (2.1ms -> sub-ms fwd per op)
_NEG_INF = -1e30


def _use_pallas() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Reference (XLA) implementation — correctness baseline + CPU path


def attention_reference(q, k, v, *, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        segment_ids=None):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    seq_q, seq_k = q.shape[2], k.shape[2]
    if causal:
        qi = jnp.arange(seq_q)[:, None] + (seq_k - seq_q)
        ki = jnp.arange(seq_k)[None, :]
        logits = jnp.where(ki <= qi, logits, _NEG_INF)
    if segment_ids is not None:
        q_seg, k_seg = segment_ids
        mask = q_seg[:, None, :, None] == k_seg[:, None, None, :]
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Whole-kv kernels (short sequences)
#
# For self-attention at s <= _WHOLE_KV_MAX_S the entire kv fits VMEM, so
# the fastest structure on v5e is NO inner loop at all: one [bq, d] x
# [s, d]T dot, one masked exp, one [bq, s] x [s, dpad] dot — fully
# static code Mosaic can pipeline. Measured (b16 h12 s1024 d64 bf16):
# 0.84 ms vs 2.4 ms for the streaming flash loop, same numerics.
#
# Key trick — no running max: softmax is shift-invariant, so a static
# shift with an overflow cap replaces the max/subtract/rescale passes
# (exp(min(s, _CAP_HI) - _CAP_SHIFT); exact as long as pre-scaled logits
# stay under _CAP_HI, which trained-LM logits do; rows whose logits ALL
# sit below _CAP_SHIFT - 87 underflow — out of scope for this path, the
# streaming kernel keeps the exact running max).
# (A ones-column-in-v MXU row-sum was tried and reverted: lane-unaligned
# 65-wide v blocks are catastrophic, and padding v to 128 lanes in XLA
# costs 1-5 ms/layer of HBM concatenate traffic.)

_WHOLE_KV_MAX_S = 2048     # s*s*4B score block stays well inside VMEM
_CAP_HI = 50.0             # logit cap: exp(50-25)=7e10 << f32 max
_CAP_SHIFT = 25.0


def _whole_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal,
                      block_q, head_dim):
    from jax.experimental import pallas as pl

    bq, d = block_q, head_dim
    sk = k_ref.shape[0]
    qi = pl.program_id(1)
    s_ = jax.lax.dot_general(q_ref[:], k_ref[:], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    e = jnp.exp(jnp.minimum(s_, _CAP_HI) - _CAP_SHIFT)
    if causal:
        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, sk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (bq, sk), 1)
        e = jnp.where(k_pos <= q_pos, e, 0.0)
    # row-sum on the VPU: cheaper than padding v with a ones column in
    # XLA (the concatenate cost ~1-5 ms/layer of HBM traffic per step)
    l = jnp.maximum(jnp.sum(e, axis=-1, keepdims=True), 1e-30)
    acc = jax.lax.dot_general(e.astype(v_ref.dtype), v_ref[:],
                              (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    lse_ref[:] = jnp.log(l) + _CAP_SHIFT


def _whole_bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, causal, block_q):
    from jax.experimental import pallas as pl

    bq = block_q
    sk = k_ref.shape[0]
    qi = pl.program_id(1)
    qq = q_ref[:]
    kk = k_ref[:]
    vv = v_ref[:]
    dd = do_ref[:]
    s_ = jax.lax.dot_general(qq, kk, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    # same _CAP_HI clamp as the forward: without it, a logit above the
    # cap makes p here disagree with the clamped forward and the
    # gradient silently explodes instead of saturating
    p = jnp.exp(jnp.minimum(s_, _CAP_HI) - lse_ref[:])
    if causal:
        q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, sk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (bq, sk), 1)
        p = jnp.where(k_pos <= q_pos, p, 0.0)
    pc = p.astype(vv.dtype)
    dp = jax.lax.dot_general(dd, vv, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = (p * (dp - delta_ref[:])).astype(qq.dtype)
    dq_ref[:] = jax.lax.dot_general(
        ds, kk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dkc = jax.lax.dot_general(
        ds, qq, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)
    dvc = jax.lax.dot_general(
        pc, dd, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    # dk/dv accumulate across the q-block grid dimension: their output
    # block index is constant in qi, so Mosaic keeps them VMEM-resident
    @pl.when(qi == 0)
    def _():
        dk_ref[:] = dkc
        dv_ref[:] = dvc

    @pl.when(qi > 0)
    def _():
        dk_ref[:] = dk_ref[:] + dkc
        dv_ref[:] = dv_ref[:] + dvc


def _whole_block_q(s: int) -> int:
    # score block [bq, s] f32 capped at ~4 MiB so several pipeline
    # buffers coexist in VMEM
    bq = max(128, min(s, (4 << 20) // (4 * s) // 128 * 128))
    while s % bq:
        bq //= 2
    return max(bq, 128)


def _attn_exact() -> bool:
    # RTPU_ATTN_EXACT=1 forces the streaming flash kernels (exact
    # running-max softmax) for workloads whose logits may exceed the
    # whole-kv path's static cap (see _CAP_HI note above). Prefer the
    # explicit ``flash_attention(..., exact=True)`` kwarg — this env
    # var is the global fallback for code that can't reach the call
    # site, and is baked in at TRACE time (set it before the first jit
    # of the attention shape; toggling afterwards does not retrace
    # cached programs).
    import os
    return bool(os.environ.get("RTPU_ATTN_EXACT"))


def _attn_debug() -> bool:
    import os
    return bool(os.environ.get("RTPU_ATTN_DEBUG"))


def _use_whole_kv(sq: int, sk: int, d: int,
                  exact: Optional[bool] = None) -> bool:
    if _attn_exact() if exact is None else exact:
        return False
    return (sq == sk and sk <= _WHOLE_KV_MAX_S and d <= 128
            and sk % 128 == 0 and sq % _whole_block_q(sq) == 0)


def _debug_check_logits(q_scaled, k):
    """Debug-mode finite-range assert for the whole-kv fast path: the
    static-shift softmax is exact only while every pre-softmax logit
    stays under ``_CAP_HI`` — beyond it the clamp silently flattens the
    distribution (and saturates gradients). With ``RTPU_ATTN_DEBUG=1``
    (or ``flash_attention(..., debug=True)``) an out-of-range logit
    fails loudly instead. Materializes the full score matrix — debug
    cost, never on the production path."""
    s_max = jnp.max(jax.lax.dot_general(
        q_scaled, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32))

    def _raise(m):
        m = float(m)
        if m > _CAP_HI:
            raise FloatingPointError(
                f"flash_attention whole-kv fast path: max scaled logit "
                f"{m:.3f} exceeds the static softmax cap "
                f"_CAP_HI={_CAP_HI} — the clamp would silently distort "
                f"the distribution. Pass exact=True (or set "
                f"RTPU_ATTN_EXACT=1) to use the exact streaming "
                f"kernel, or rescale the logits.")

    if isinstance(s_max, jax.core.Tracer):
        # under jit the check runs at execution time via callback (the
        # failure surfaces as a runtime callback error)
        jax.debug.callback(_raise, s_max)
    else:
        _raise(s_max)


def _whole_forward(q, k, v, causal, interpret=False):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _whole_block_q(sq)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    kernel = functools.partial(_whole_fwd_kernel, causal=causal,
                               block_q=bq, head_dim=d)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _whole_backward(res, g, *, causal, interpret=False):
    from jax.experimental import pallas as pl

    q, k, v, out, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _whole_block_q(sq)
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1)  # [b,h,sq]
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    dof = g.reshape(b * h, sq, d)
    lsef = lse.reshape(b * h, sq, 1)
    deltaf = delta.reshape(b * h, sq, 1)
    kernel = functools.partial(_whole_bwd_kernel, causal=causal,
                               block_q=bq)
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, 1), lambda i, j: (i, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# Pallas forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_k, seq_k):
    # refs: q [bq, d]; k/v [seq_k, d]; o [bq, d]; lse [bq, 1]
    # (lse keeps a trailing lane dim — TPU blocks must be >=2D tiles)
    #
    # VPU economy (the measured bottleneck at d=64 on v5e — the softmax
    # passes cost as much as all the MXU work):
    #   - dots take NATIVE (bf16) inputs with f32 accumulation; an f32
    #     upcast first would force f32 MXU matmuls (~4x slower)
    #   - sm_scale is pre-folded into q by the wrapper (sm_scale == 1.0
    #     here), deleting a full [bq, block_k] multiply per kv block
    #   - the kv loop is SPLIT: blocks strictly below the diagonal skip
    #     the iota/compare/select masking entirely; only the ragged
    #     diagonal blocks pay for it
    from jax.experimental import pallas as pl

    bq, d = q_ref.shape
    q = q_ref[:]
    qi = pl.program_id(1)

    m = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    num_kv = seq_k // block_k

    def body(j, carry, masked):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if sm_scale != 1.0:
            s = s * sm_scale
        if masked:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    if causal:
        # [0, clean): fully below the diagonal — unmasked.
        # [clean, needed): intersect the diagonal — masked.
        clean = (qi * bq) // block_k
        needed = jnp.minimum(pl.cdiv((qi + 1) * bq, block_k), num_kv)
        carry = jax.lax.fori_loop(
            0, clean, lambda j, c: body(j, c, False), (m, l, acc))
        m, l, acc = jax.lax.fori_loop(
            clean, needed, lambda j, c: body(j, c, True), carry)
    else:
        m, l, acc = jax.lax.fori_loop(
            0, num_kv, lambda j, c: body(j, c, False), (m, l, acc))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l)


def _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                   interpret=False):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    assert sq % bq == 0 and sk % bk == 0, (
        f"seq lengths must be multiples of block sizes ({sq}%{bq}, {sk}%{bk})"
        " — pad to tile boundaries (fixed shapes keep XLA from recompiling)")
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_k=bk, seq_k=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


# ---------------------------------------------------------------------------
# Pallas backward


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale, causal, block_q, seq_q):
    from jax.experimental import pallas as pl

    bk, d = k_ref.shape
    kj = pl.program_id(1)
    # native-dtype (bf16) dot inputs, f32 accumulation, pre-scaled q,
    # split masked/clean loops — see _fwd_kernel
    k = k_ref[:]
    v = v_ref[:]
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    num_q = seq_q // block_q

    def body(i, carry, masked):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :]
        do = do_ref[pl.ds(i * block_q, block_q), :]
        lse = lse_ref[pl.ds(i * block_q, block_q), :]      # [bq, 1]
        delta = delta_ref[pl.ds(i * block_q, block_q), :]  # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if sm_scale != 1.0:
            s = s * sm_scale
        if masked:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_pos = kj * bk + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        pc = p.astype(do.dtype)
        dv = dv + jax.lax.dot_general(pc, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype) if sm_scale == 1.0 else \
            (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    if causal:
        # [start_q, diag_end): intersect the diagonal — masked.
        # [diag_end, num_q): fully below — unmasked.
        start_q = (kj * bk) // block_q
        diag_end = jnp.minimum(pl.cdiv((kj + 1) * bk, block_q), num_q)
        carry = jax.lax.fori_loop(
            start_q, diag_end, lambda i, c: body(i, c, True), (dk, dv))
        dk, dv = jax.lax.fori_loop(
            diag_end, num_q, lambda i, c: body(i, c, False), carry)
    else:
        dk, dv = jax.lax.fori_loop(
            0, num_q, lambda i, c: body(i, c, False), (dk, dv))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, sm_scale, causal, block_k, seq_k):
    from jax.experimental import pallas as pl

    bq, d = q_ref.shape
    qi = pl.program_id(1)
    # native-dtype (bf16) dot inputs, f32 accumulation, pre-scaled q,
    # split masked/clean loops — see _fwd_kernel
    q = q_ref[:]
    do = do_ref[:]
    lse = lse_ref[:]      # [bq, 1]
    delta = delta_ref[:]  # [bq, 1]
    dq = jnp.zeros((bq, d), jnp.float32)

    num_kv = seq_k // block_k

    def body(j, dq, masked):
        k = k_ref[pl.ds(j * block_k, block_k), :]
        v = v_ref[pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if sm_scale != 1.0:
            s = s * sm_scale
        if masked:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype) if sm_scale == 1.0 else \
            (p * (dp - delta) * sm_scale).astype(k.dtype)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    if causal:
        clean = (qi * bq) // block_k
        needed = jnp.minimum(pl.cdiv((qi + 1) * bq, block_k), num_kv)
        dq = jax.lax.fori_loop(
            0, clean, lambda j, c: body(j, c, False), dq)
        dq = jax.lax.fori_loop(
            clean, needed, lambda j, c: body(j, c, True), dq)
    else:
        dq = jax.lax.fori_loop(
            0, num_kv, lambda j, c: body(j, c, False), dq)
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _flash_backward(res, g, *, sm_scale, causal, block_q, block_k,
                    interpret=False):
    from jax.experimental import pallas as pl

    q, k, v, out, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1)  # [b,h,sq]
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    dof = g.reshape(b * h, sq, d)
    lsef = lse.reshape(b * h, sq, 1)
    deltaf = delta.reshape(b * h, sq, 1)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block_q=bq, seq_q=sq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, sk // bk),
        in_specs=[
            pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sq, 1), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sq, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    dq_kernel = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block_k=bk, seq_k=sk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, 1), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# Public op with custom VJP


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_attention(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                     exact):
    out, _ = _dispatch_forward(q, k, v, sm_scale, causal, block_q, block_k,
                               interpret, exact)
    return out


def _dispatch_forward(q, k, v, sm_scale, causal, block_q, block_k,
                      interpret, exact=None):
    if sm_scale == 1.0 and _use_whole_kv(q.shape[2], k.shape[2],
                                         q.shape[3], exact):
        return _whole_forward(q, k, v, causal, interpret)
    return _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                          interpret)


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k, interpret,
                    exact):
    out, lse = _dispatch_forward(q, k, v, sm_scale, causal, block_q,
                                 block_k, interpret, exact)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, interpret, exact,
                    res, g):
    q, k, v, out, lse = res
    if sm_scale == 1.0 and _use_whole_kv(q.shape[2], k.shape[2],
                                         q.shape[3], exact):
        return _whole_backward(res, g, causal=causal, interpret=interpret)
    return _flash_backward(res, g, sm_scale=sm_scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    force_pallas: Optional[bool] = None,
                    interpret: bool = False,
                    exact: Optional[bool] = None,
                    debug: Optional[bool] = None):
    """Fused attention. [b, h, s, d] → [b, h, s, d].

    On TPU runs the Pallas kernel; elsewhere falls back to the XLA reference
    (still fused reasonably by XLA on CPU for tests).

    ``exact`` picks the softmax numerics explicitly: ``True`` forces
    the streaming flash kernels (exact running-max softmax — use for
    workloads whose scaled logits may exceed the whole-kv path's
    static ``_CAP_HI`` cap), ``False`` allows the whole-kv fast path
    wherever its shape constraints hold, and ``None`` (default) defers
    to the ``RTPU_ATTN_EXACT`` env var. Per-call and trace-stable,
    unlike the env var, which only applies at first trace.

    ``debug`` (default: env ``RTPU_ATTN_DEBUG``) adds a finite-range
    assert when the whole-kv path is taken: any pre-softmax logit
    above ``_CAP_HI`` raises ``FloatingPointError`` instead of being
    silently clamped. Costs a full score-matrix pass — debugging only.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    use = _use_pallas() if force_pallas is None else force_pallas
    # Auto mode falls back to XLA for shapes the kernel can't tile: seq not
    # divisible by the (clamped) block sizes, or blocks under the TPU
    # sublane minimum (16 covers bf16's (16,128) tile). An explicit
    # force_pallas=True is honored — the kernel's own asserts surface.
    sq, sk = q.shape[2], k.shape[2]
    if force_pallas is None and use:
        # clamp blocks to a divisor of the sequence before giving up —
        # e.g. s=3840 doesn't divide by the 512 default but does by 256,
        # and the XLA fallback would materialize the full S x S scores
        def _fit(block, s):
            b = min(block, s)
            while b >= 16 and s % b:
                b //= 2
            return b
        bq, bk = _fit(block_q, sq), _fit(block_k, sk)
        if (bq < 16 or bk < 16 or sq % bq or sk % bk
                or bq % 16 or bk % 16):
            use = False
        else:
            block_q, block_k = bq, bk
    if not use and not interpret:
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    # Fold the softmax scale into q OUTSIDE the kernel (one [b,h,s,d]
    # multiply, and autodiff routes the matching dq scale through it) so
    # the kernels skip a full [bq, block_k] multiply per kv block.
    q = (q * sm_scale).astype(q.dtype)
    if (debug if debug is not None else _attn_debug()) and \
            _use_whole_kv(sq, sk, q.shape[3], exact):
        _debug_check_logits(q, k)
    return _flash_attention(q, k, v, 1.0, causal, block_q, block_k,
                            interpret, exact)


# ---------------------------------------------------------------------------
# Incremental decode + paged KV cache (LLM serving, docs/LLM_SERVING.md)
#
# Training attention above recomputes every key/value each step; online
# inference must not. The serve LLM engine keeps KV in fixed-size BLOCKS
# (a paged cache, vLLM-style): per sequence a block table maps logical
# token positions to physical pages, so sequences grow without
# contiguous reallocation and freed pages are reusable immediately.
#
# Layouts (chosen so a scatter/gather is one advanced-index op):
#   contiguous cache   k/v: [B, S_max, Hkv, D]
#   paged cache        k/v pages: [P, bs, Hkv, D]; block_tables [B, NB]
#   lengths            [B] int32 — valid cache entries per sequence
#
# Three compute paths, all numerically equivalent (tier-1 gated in
# tests/test_llm_serving.py):
#   decode_attention            contiguous masked reference (XLA, CPU ok)
#   paged_attention_reference   gather pages -> decode_attention
#   paged_attention_decode      Pallas kernel: scalar-prefetched block
#                               tables index pages straight from HBM,
#                               flash-style online softmax per block —
#                               the cache is never materialized
#                               contiguously (interpret=True on CPU)


def _repeat_kv(k, rep: int, axis: int = 1):
    """Broadcast each kv head over its query group (GQA)."""
    return k if rep == 1 else jnp.repeat(k, rep, axis=axis)


def decode_attention(q, k_cache, v_cache, lengths, *,
                     sm_scale: Optional[float] = None,
                     q_positions=None):
    """Attention of new-token queries against a (padded) KV cache.

    q: [B, H, S_new, D] — the S_new newest tokens' queries; the cache
    already contains their keys/values (positions
    ``lengths - S_new .. lengths - 1``).
    k_cache/v_cache: [B, S_max, Hkv, D]; lengths: [B] int32 — valid
    entries INCLUDING the new tokens. Causal within the new tokens,
    full visibility over the prefix, masked past ``lengths``. GQA when
    Hkv < H (H must be a multiple of Hkv). ``q_positions`` ([B, S_new]
    int32, optional) overrides each query row's absolute position —
    right-padded prefill passes the real positions (and -1 for padding
    rows, whose output is discarded). Returns [B, H, S_new, D].
    """
    B, H, S_new, D = q.shape
    Hkv = k_cache.shape[2]
    if sm_scale is None:
        sm_scale = D ** -0.5
    k = _repeat_kv(k_cache.transpose(0, 2, 1, 3), H // Hkv)  # [B,H,S,D]
    v = _repeat_kv(v_cache.transpose(0, 2, 1, 3), H // Hkv)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    S_max = k_cache.shape[1]
    # query i (0-based among the new tokens) sits at absolute position
    # lengths - S_new + i and may attend to absolute positions <= its own
    if q_positions is None:
        q_positions = (lengths[:, None] - S_new) + \
            jnp.arange(S_new)[None, :]                     # [B,S_new]
    q_pos = q_positions[..., None]                         # [B,S_new,1]
    k_pos = jnp.arange(S_max)[None, None, :]               # [1,1,S_max]
    mask = (k_pos <= q_pos)[:, None]                       # [B,1,S_new,S_max]
    logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


def append_kv_pages(k_new, v_new, k_pages, v_pages, block_tables,
                    lengths, valid=None):
    """Scatter new keys/values into their pages.

    k_new/v_new: [B, S, Hkv, D] written at logical positions
    ``lengths .. lengths + S - 1`` of each sequence; ``valid`` ([B, S]
    bool, optional) routes padding tokens to the reserved null page 0
    instead (batch/length bucketing for jit). Returns updated
    (k_pages, v_pages). Distinct sequences own distinct pages, so the
    scatter indices never collide except in the null page (scratch).
    """
    B, S = k_new.shape[:2]
    bs = k_pages.shape[1]
    pos = lengths[:, None] + jnp.arange(S)[None, :]        # [B, S]
    page = jnp.take_along_axis(block_tables, pos // bs, axis=1)
    slot = pos % bs
    if valid is not None:
        page = jnp.where(valid, page, 0)
        slot = jnp.where(valid, slot, 0)
    k_pages = k_pages.at[page, slot].set(k_new)
    v_pages = v_pages.at[page, slot].set(v_new)
    return k_pages, v_pages


def paged_gather(pages, block_tables):
    """Pages -> per-sequence (padded) contiguous cache:
    [P, bs, Hkv, D] + [B, NB] -> [B, NB*bs, Hkv, D]."""
    B, NB = block_tables.shape
    bs = pages.shape[1]
    out = pages[block_tables]                              # [B,NB,bs,Hkv,D]
    return out.reshape(B, NB * bs, *pages.shape[2:])


def paged_attention_reference(q, k_pages, v_pages, block_tables,
                              lengths, *,
                              sm_scale: Optional[float] = None):
    """Single-token decode against the paged cache, via gather (the
    correctness baseline for the Pallas kernel and the CPU fallback).

    q: [B, H, D] (one query token per sequence); returns [B, H, D].
    """
    out = decode_attention(q[:, :, None, :],
                           paged_gather(k_pages, block_tables),
                           paged_gather(v_pages, block_tables),
                           lengths, sm_scale=sm_scale)
    return out[:, :, 0, :]


def _paged_decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, block_size,
                         num_blocks):
    """One (sequence, kv-head, page) grid step of paged flash decode.

    The page refs were DMA'd by the scalar-prefetched index map (the
    block table picks the physical page per grid step), so the body is
    plain flash: one [G, bs] dot, online softmax, [G, D] accumulate.
    Fully-masked pages (past the sequence length) contribute zero
    because masked logits are a large-but-finite negative, never -inf.
    """
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[:]                                  # [G, D]
    k = k_ref[:]                                  # [bs, D]
    v = v_ref[:]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    pos = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    s = jnp.where(pos < len_ref[b], s, _NEG_INF)
    m_prev, l_prev = m_ref[:], l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[:], l_ref[:] = m_new, l_new
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == num_blocks - 1)
    def _():
        o_ref[:] = (acc_ref[:]
                    / jnp.maximum(l_ref[:], 1e-30)).astype(o_ref.dtype)


def paged_attention_decode(q, k_pages, v_pages, block_tables, lengths,
                           *, sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """Pallas paged-attention decode: q [B, H, D] against block-table-
    addressed pages, without gathering the cache into contiguous HBM.

    Grid (B, Hkv, NB); the block table + lengths ride scalar prefetch
    so each grid step's BlockSpec index map DMAs exactly the page it
    needs (pallas_guide: PrefetchScalarGridSpec). Off-TPU (and not
    ``interpret``) this falls back to the gather reference — numerics
    are identical (gated in tests), so callers never branch.

    GQA note: the G = H // Hkv query heads of one kv head form the
    kernel's [G, D] q block; small G under-fills TPU sublanes — pad
    query heads toward G >= 8 for peak MXU use on real hardware.
    """
    if interpret is None:
        interpret = False
        if not _use_pallas():
            return paged_attention_reference(
                q, k_pages, v_pages, block_tables, lengths,
                sm_scale=sm_scale)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    P, bs, Hkv, _ = k_pages.shape
    NB = block_tables.shape[1]
    G = H // Hkv
    if sm_scale is None:
        sm_scale = D ** -0.5
    qf = (q * sm_scale).astype(q.dtype).reshape(B, Hkv, G, D)
    kernel = functools.partial(_paged_decode_kernel, block_size=bs,
                               num_blocks=NB)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, NB),
        in_specs=[
            pl.BlockSpec((None, None, G, D),
                         lambda b, h, j, bt, ln: (b, h, 0, 0)),
            pl.BlockSpec((None, bs, None, D),
                         lambda b, h, j, bt, ln: (bt[b, j], 0, h, 0)),
            pl.BlockSpec((None, bs, None, D),
                         lambda b, h, j, bt, ln: (bt[b, j], 0, h, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, G, D),
                               lambda b, h, j, bt, ln: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, 1), jnp.float32),
            pltpu.VMEM((G, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, D), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      qf, k_pages, v_pages)
    return out.reshape(B, H, D)


def cached_attention(q, k_new, v_new, cache, seq_lengths, *,
                     sm_scale: Optional[float] = None, valid=None):
    """Shared incremental-attention step for the model decode paths
    (models/gpt2.py, models/llama.py).

    q/k_new/v_new: [B, S, H|Hkv, D] projections of the S newest tokens
    (q head-major is the CALLER's concern — here everything is token-
    major, matching the cache layouts). ``cache`` is one layer's cache:

      {"k": [B,S_max,Hkv,D], "v": ...}                    contiguous
      {"k_pages": [P,bs,Hkv,D], "v_pages": ...,
       "block_tables": [B,NB]}                            paged

    ``seq_lengths`` [B] counts valid cache entries BEFORE this call
    (i.e. the prefix length); ``valid`` ([B, S] bool, optional) marks
    real tokens when the caller padded S to a bucket — padding kv is
    routed to the paged cache's null page and masked out of attention
    by the lengths. Appends the new kv, attends causally, and returns
    (out [B, S, H, D], updated cache dict).
    """
    B, S = q.shape[:2]
    q_positions = None
    if valid is not None:
        new_len = seq_lengths + jnp.sum(valid.astype(jnp.int32), axis=1)
        # right-padding: real token i sits at absolute seq_lengths + i;
        # padding rows attend to nothing real (position -1)
        q_positions = jnp.where(
            valid, seq_lengths[:, None] + jnp.arange(S)[None, :], -1)
    else:
        new_len = seq_lengths + S
    if "k_pages" in cache:
        k_pages, v_pages = append_kv_pages(
            k_new, v_new, cache["k_pages"], cache["v_pages"],
            cache["block_tables"], seq_lengths, valid=valid)
        out = decode_attention(
            q.transpose(0, 2, 1, 3),
            paged_gather(k_pages, cache["block_tables"]),
            paged_gather(v_pages, cache["block_tables"]),
            new_len, sm_scale=sm_scale, q_positions=q_positions)
        new_cache = dict(cache, k_pages=k_pages, v_pages=v_pages)
    else:
        pos = seq_lengths[:, None] + jnp.arange(S)[None, :]
        bidx = jnp.arange(B)[:, None]
        if valid is not None:
            # padded tokens must not clobber cache slots a later real
            # token will own: clamp their write position in place
            vm = valid[..., None, None]
            k_new = jnp.where(vm, k_new, cache["k"][bidx, pos])
            v_new = jnp.where(vm, v_new, cache["v"][bidx, pos])
        k_cache = cache["k"].at[bidx, pos].set(k_new)
        v_cache = cache["v"].at[bidx, pos].set(v_new)
        out = decode_attention(q.transpose(0, 2, 1, 3), k_cache,
                               v_cache, new_len, sm_scale=sm_scale,
                               q_positions=q_positions)
        new_cache = dict(cache, k=k_cache, v=v_cache)
    return out.transpose(0, 2, 1, 3), new_cache
