"""Flash attention for TPU (Pallas) with an XLA fallback.

No reference analogue — the reference delegates all kernel work to
torch/CUDA (SURVEY.md §2.6: TP/SP absent, math lives inside train_func).
For a TPU-native framework the fused attention kernel is a core op: it keeps
the S×S score matrix out of HBM (block-online softmax in VMEM), which is what
makes long-context training possible at all.

Algorithm: standard flash attention v2 tiling.
  forward: for each q block, stream kv blocks; online softmax keeps running
  max m and normalizer l; out = acc / l; LSE saved for backward.
  backward: two kernels — dkv (grid over kv blocks, loop q) and dq (grid over
  q blocks, loop kv) — recompute p from saved LSE.

Shapes: [batch, heads, seq, head_dim]; block sizes default 128 (MXU tile).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _use_pallas() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# Reference (XLA) implementation — correctness baseline + CPU path


def attention_reference(q, k, v, *, causal: bool = False,
                        sm_scale: Optional[float] = None,
                        segment_ids=None):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    seq_q, seq_k = q.shape[2], k.shape[2]
    if causal:
        qi = jnp.arange(seq_q)[:, None] + (seq_k - seq_q)
        ki = jnp.arange(seq_k)[None, :]
        logits = jnp.where(ki <= qi, logits, _NEG_INF)
    if segment_ids is not None:
        q_seg, k_seg = segment_ids
        mask = q_seg[:, None, :, None] == k_seg[:, None, None, :]
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, sm_scale, causal,
                block_k, seq_k):
    # refs: q [bq, d]; k/v [seq_k, d]; o [bq, d]; lse [bq, 1]
    # (lse keeps a trailing lane dim — TPU blocks must be >=2D tiles)
    from jax.experimental import pallas as pl

    bq, d = q_ref.shape
    q = q_ref[:].astype(jnp.float32) * sm_scale
    qi = pl.program_id(1)

    m = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((bq, 1), jnp.float32)
    acc = jnp.zeros((bq, d), jnp.float32)

    num_kv = seq_k // block_k
    if causal:
        # kv blocks strictly above the diagonal contribute nothing
        num_kv_needed = jnp.minimum(
            pl.cdiv((qi + 1) * bq, block_k), num_kv)
    else:
        num_kv_needed = num_kv

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_kv_needed, body, (m, l, acc))
    l = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l).astype(o_ref.dtype)
    lse_ref[:] = m + jnp.log(l)


def _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                   interpret=False):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    assert sq % bq == 0 and sk % bk == 0, (
        f"seq lengths must be multiples of block sizes ({sq}%{bq}, {sk}%{bk})"
        " — pad to tile boundaries (fixed shapes keep XLA from recompiling)")
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    kernel = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                               block_k=bk, seq_k=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


# ---------------------------------------------------------------------------
# Pallas backward


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, sm_scale, causal, block_q, seq_q):
    from jax.experimental import pallas as pl

    bk, d = k_ref.shape
    kj = pl.program_id(1)
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    dk = jnp.zeros((bk, d), jnp.float32)
    dv = jnp.zeros((bk, d), jnp.float32)

    num_q = seq_q // block_q
    if causal:
        start_q = (kj * bk) // block_q
    else:
        start_q = 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(i * block_q, block_q), :]      # [bq, 1]
        delta = delta_ref[pl.ds(i * block_q, block_q), :]  # [bq, 1]
        s = jax.lax.dot_general(q * sm_scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_pos = kj * bk + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk, dv = jax.lax.fori_loop(start_q, num_q, body, (dk, dv))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, *, sm_scale, causal, block_k, seq_k):
    from jax.experimental import pallas as pl

    bq, d = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]      # [bq, 1]
    delta = delta_ref[:]  # [bq, 1]
    dq = jnp.zeros((bq, d), jnp.float32)

    num_kv = seq_k // block_k
    if causal:
        num_kv_needed = jnp.minimum(
            pl.cdiv((qi + 1) * bq, block_k), num_kv)
    else:
        num_kv_needed = num_kv

    def body(j, dq):
        k = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q * sm_scale, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kv_needed, body, dq)
    dq_ref[:] = dq.astype(dq_ref.dtype)


def _flash_backward(res, g, *, sm_scale, causal, block_q, block_k,
                    interpret=False):
    from jax.experimental import pallas as pl

    q, k, v, out, lse = res
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1)  # [b,h,sq]
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    dof = g.reshape(b * h, sq, d)
    lsef = lse.reshape(b * h, sq, 1)
    deltaf = delta.reshape(b * h, sq, 1)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale,
                                   causal=causal, block_q=bq, seq_q=sq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b * h, sk // bk),
        in_specs=[
            pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sq, 1), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sq, 1), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bk, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    dq_kernel = functools.partial(_bwd_dq_kernel, sm_scale=sm_scale,
                                  causal=causal, block_k=bk, seq_k=sk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b * h, sq // bq),
        in_specs=[
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bq, 1), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, bq, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# ---------------------------------------------------------------------------
# Public op with custom VJP


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                            interpret)
    return out


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, sm_scale, causal, block_q, block_k,
                              interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, interpret, res, g):
    return _flash_backward(res, g, sm_scale=sm_scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           interpret=interpret)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    force_pallas: Optional[bool] = None,
                    interpret: bool = False):
    """Fused attention. [b, h, s, d] → [b, h, s, d].

    On TPU runs the Pallas kernel; elsewhere falls back to the XLA reference
    (still fused reasonably by XLA on CPU for tests)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    use = _use_pallas() if force_pallas is None else force_pallas
    # Auto mode falls back to XLA for shapes the kernel can't tile: seq not
    # divisible by the (clamped) block sizes, or blocks under the TPU
    # sublane minimum (16 covers bf16's (16,128) tile). An explicit
    # force_pallas=True is honored — the kernel's own asserts surface.
    sq, sk = q.shape[2], k.shape[2]
    if force_pallas is None and use:
        bq, bk = min(block_q, sq), min(block_k, sk)
        if (sq % bq or sk % bk or bq % 16 or bk % 16):
            use = False
    if not use and not interpret:
        return attention_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    return _flash_attention(q, k, v, sm_scale, causal, block_q, block_k,
                            interpret)
