"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Absent from the reference (SURVEY.md §5.7: no ring attention / sequence
parallelism anywhere in train/ or util/) — this is a required TPU-native
capability: long sequences are sharded over the ``sp`` axis, each device
holds S/sp query and kv shards, and kv shards rotate around the ICI ring via
``ppermute`` while each device accumulates attention with an online softmax
(m, l running statistics) — compute on the current kv shard overlaps the
transfer of the next (XLA overlaps the collective-permute with the einsum).

Memory: O(S/sp · d) per device instead of O(S²) — sequence length scales
linearly with the number of devices in the ring.

Usage: inside shard_map with sequences sharded over axis ``sp``:
    out = ring_attention(q, k, v, axis_name="sp", causal=True)
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _block_attend(q, k, v, sm_scale, mask):
    """One q-shard × kv-shard attention block, returning unnormalized
    (acc, m, l) statistics for online-softmax merging."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [b,h,q]
    # guard fully-masked rows (all -inf): exp underflows to 0, fine
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return acc, m, l


def _merge(acc, m, l, acc2, m2, l2):
    m_new = jnp.maximum(m, m2)
    a1 = jnp.exp(m - m_new)
    a2 = jnp.exp(m2 - m_new)
    acc_new = acc * a1[..., None] + acc2 * a2[..., None]
    l_new = l * a1 + l2 * a2
    return acc_new, m_new, l_new


def ring_attention(q, k, v, *, axis_name: str = "sp",
                   causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Attention over sequences sharded on ``axis_name``.

    Must be called inside shard_map/pjit with q/k/v sequence dims sharded
    over the ring axis. Shapes per device: [b, h, s_local, d].
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    b, h, _, d = q.shape

    acc = jnp.zeros(q.shape[:3] + (d,), jnp.float32)
    m = jnp.full(q.shape[:3], _NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3], jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def make_mask(kv_idx):
        if not causal:
            return None
        q_pos = my_idx * s_local + jnp.arange(s_local)[:, None]
        k_pos = kv_idx * s_local + jnp.arange(s_local)[None, :]
        return (k_pos <= q_pos)[None, None]  # [1,1,q,k]

    def step(i, carry):
        acc, m, l, k_cur, v_cur = carry
        # which shard do we currently hold? it started at (my_idx) and has
        # been rotated i times: shard index = (my_idx - i) mod size
        kv_idx = (my_idx - i) % axis_size
        if causal:
            # skip blocks entirely in the future (kv_idx > my_idx)
            mask = make_mask(kv_idx)
            acc2, m2, l2 = _block_attend(q, k_cur, v_cur, sm_scale, mask)
            skip = kv_idx > my_idx
            acc2 = jnp.where(skip, 0.0, acc2)
            m2 = jnp.where(skip, _NEG_INF, m2)
            l2 = jnp.where(skip, 0.0, l2)
        else:
            acc2, m2, l2 = _block_attend(q, k_cur, v_cur, sm_scale, None)
        acc, m, l = _merge(acc, m, l, acc2, m2, l2)
        # rotate kv to the next device; overlaps with the next iteration's
        # compute under XLA's async collective-permute
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_next, v_next

    acc, m, l, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (acc, m, l, k, v))
    l = jnp.maximum(l, 1e-30)
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, axis_name: str = "sp",
                           causal: bool = False,
                           sm_scale: Optional[float] = None):
    """Convenience wrapper: runs ring_attention under shard_map on `mesh`
    with [b, h, s, d] inputs sharded over the sequence dim."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.jax_compat import shard_map

    spec = P(None, None, axis_name, None)

    def inner(q, k, v):
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              sm_scale=sm_scale)

    return shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)
