"""Grafana dashboard generation from the metric registry.

Reference analogue: dashboard/modules/metrics/grafana_dashboard_factory.py
— curated Grafana boards generated from the declared metric set, so the
Prometheus endpoint (dashboard.py /metrics) comes with ready-to-import
dashboards instead of a bare scrape target.

The panel inventory mirrors the gauge families exported by
``_cluster_gauges``/``_node_gauges``/``util.metrics``; regenerate with
``write_dashboards()`` (the CLI exposes it as
``ray-tpu grafana --out DIR``).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Tuple

_DATASOURCE = {"type": "prometheus", "uid": "${datasource}"}


def _panel(title: str, exprs: List[Tuple[str, str]], *, unit: str = "short",
           stacked: bool = False) -> Dict[str, Any]:
    # id/gridPos are assigned by _layout(), which owns placement
    return {
        "title": title,
        "type": "timeseries",
        "datasource": dict(_DATASOURCE),
        "fieldConfig": {
            "defaults": {
                "unit": unit,
                "custom": {"fillOpacity": 10,
                           "stacking": {"mode": "normal"}
                           if stacked else {"mode": "none"}},
            },
            "overrides": [],
        },
        "targets": [
            {"expr": expr, "legendFormat": legend,
             "datasource": dict(_DATASOURCE), "refId": chr(ord("A") + i)}
            for i, (expr, legend) in enumerate(exprs)
        ],
    }


def _dashboard(uid: str, title: str,
               panels: List[Dict[str, Any]]) -> Dict[str, Any]:
    return {
        "uid": uid,
        "title": title,
        "tags": ["ray-tpu", "generated"],
        "timezone": "browser",
        "schemaVersion": 39,
        "refresh": "15s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource",
            "type": "datasource",
            "query": "prometheus",
            "current": {},
        }]},
        "panels": panels,
    }


def _layout(panels: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Two-column grid; ids and positions assigned in order."""
    for i, p in enumerate(panels):
        p["id"] = i + 1
        p["gridPos"] = {"x": (i % 2) * 12, "y": (i // 2) * 8,
                        "w": 12, "h": 8}
    return panels


def core_dashboard() -> Dict[str, Any]:
    return _dashboard("ray-tpu-core", "ray_tpu // Core", _layout([
        _panel("Alive nodes", [
            ("ray_tpu_cluster_nodes_alive", "alive"),
            ("ray_tpu_cluster_nodes_total", "registered")]),
        _panel("Actors", [
            ("ray_tpu_cluster_actors_alive", "alive"),
            ("ray_tpu_cluster_actors_total", "total")]),
        _panel("Cluster resources", [
            ('ray_tpu_cluster_resource_total{resource=~"CPU|TPU"}',
             "{{resource}} total"),
            ('ray_tpu_cluster_resource_available{resource=~"CPU|TPU"}',
             "{{resource}} available")]),
        _panel("Task throughput (cluster)", [
            ("sum(rate(ray_tpu_node_scheduler_tasks_dispatched_total[1m]))",
             "dispatched/s")], unit="ops"),
    ]))


def scheduler_dashboard() -> Dict[str, Any]:
    return _dashboard("ray-tpu-scheduler", "ray_tpu // Scheduler", _layout([
        _panel("Pending tasks by node", [
            ("ray_tpu_node_scheduler_tasks_pending", "{{node}}")],
            stacked=True),
        _panel("Running tasks by node", [
            ("ray_tpu_node_scheduler_tasks_running", "{{node}}")],
            stacked=True),
        _panel("Dispatch rate by node", [
            ("rate(ray_tpu_node_scheduler_tasks_dispatched_total[1m])",
             "{{node}}")], unit="ops"),
        _panel("Spillbacks", [
            ("rate(ray_tpu_node_scheduler_tasks_spilled_back_total[5m])",
             "{{node}}")], unit="ops"),
        _panel("Workers", [
            ("ray_tpu_node_scheduler_workers_alive", "{{node}} alive"),
            ("ray_tpu_node_scheduler_workers_idle", "{{node}} idle")]),
        _panel("Event-loop lag", [
            ("ray_tpu_node_scheduler_event_loop_lag_s", "{{node}} lag"),
            ("ray_tpu_node_scheduler_event_loop_lag_peak_s",
             "{{node}} peak")], unit="s"),
    ]))


def object_store_dashboard() -> Dict[str, Any]:
    return _dashboard("ray-tpu-objects", "ray_tpu // Object store", _layout([
        _panel("Store bytes by node", [
            ("ray_tpu_node_object_store_used_bytes", "{{node}} used"),
            ("ray_tpu_node_object_store_capacity", "{{node}} capacity")],
            unit="bytes"),
        _panel("Objects created", [
            ("rate(ray_tpu_node_object_store_num_created[1m])",
             "{{node}}")], unit="ops"),
        _panel("Spill activity", [
            ("ray_tpu_node_object_store_spilled_objects",
             "{{node}} spilled"),
            ("rate(ray_tpu_node_object_store_restored_bytes_total[1m])",
             "{{node}} restore B/s")]),
        _panel("Transfer in flight", [
            ("ray_tpu_node_object_store_pull_inflight_bytes",
             "{{node}} pull bytes"),
            ("ray_tpu_node_object_store_pushes_inflight",
             "{{node}} pushes")]),
    ]))


def node_dashboard() -> Dict[str, Any]:
    return _dashboard("ray-tpu-nodes", "ray_tpu // Nodes & TPU", _layout([
        _panel("Host CPU", [
            ("ray_tpu_node_cpu_percent", "{{node}}")], unit="percent"),
        _panel("Host memory", [
            ("ray_tpu_node_mem_available_bytes", "{{node}} available"),
            ("ray_tpu_node_mem_total_bytes", "{{node}} total")],
            unit="bytes"),
        _panel("TPU chips", [
            ("ray_tpu_node_tpu_num_chips", "{{node}} chips"),
            ("ray_tpu_node_tpu_chips_available", "{{node}} free")]),
        _panel("Disk free", [
            ("ray_tpu_node_disk_free_bytes", "{{node}}")], unit="bytes"),
    ]))


def generate_dashboards() -> Dict[str, Dict[str, Any]]:
    """All generated boards keyed by file stem."""
    return {
        "ray_tpu_core": core_dashboard(),
        "ray_tpu_scheduler": scheduler_dashboard(),
        "ray_tpu_object_store": object_store_dashboard(),
        "ray_tpu_nodes": node_dashboard(),
    }


def write_dashboards(out_dir: str) -> List[str]:
    """Write importable Grafana JSON files; returns the paths."""
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for stem, doc in generate_dashboards().items():
        path = os.path.join(out_dir, f"{stem}.json")
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
        paths.append(path)
    return paths
