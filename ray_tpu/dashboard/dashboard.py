"""Dashboard — HTTP observability + job REST API.

Reference analogue: dashboard/dashboard.py + head.py (aiohttp module
registry) and modules/{node,actor,job,metrics,healthz}. Endpoints:

  GET  /api/cluster_status   resources + node/actor/task summary
  GET  /api/nodes            node table
  GET  /api/actors           actor table
  GET  /api/tasks            paginated task table (state/name/job_id
                             filters, limit + continuation token)
  GET  /api/objects          cluster object listing (per-raylet index)
  GET  /api/summary/tasks    per-function task aggregation
  GET  /api/timeline         merged chrome-trace task timeline (+ ring
                             drop counter)
  GET  /api/traces           paginated trace summaries
  GET  /api/trace/<id>       one trace: span tree + critical-path
                             phase attribution + completeness verdict
  GET  /api/serve/metrics    live serve panel (queue/shed/p99)
  GET  /api/gameday          last game-day SLO report (client-side
                             p50/p99/p99.9, ledger counts, budget
                             burn, reconciliation verdict)
  GET  /api/jobs/            job list      POST /api/jobs/  submit
  GET  /api/jobs/<id>        job info      GET /api/jobs/<id>/logs
  POST /api/jobs/<id>/stop
  GET  /metrics              Prometheus exposition (util.metrics hub
                             + cluster/node/serve gauges)
  GET  /healthz
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional


class DashboardActor:
    """Runs the HTTP server inside a detached actor (like the Serve
    proxy), so `ray-tpu start --head` and tests manage it uniformly."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host, self.port = host, port
        self._server: Optional[ThreadingHTTPServer] = None
        self._start_server()

    def _start_server(self):

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, doc: Any):
                data = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _text(self, code: int, text: str,
                      ctype: str = "text/plain"):
                data = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                try:
                    self._route("GET", None)
                except Exception as e:
                    self._json(500, {"error": repr(e)})

            def do_POST(self):
                self._with_body("POST")

            def do_PUT(self):
                self._with_body("PUT")

            def _with_body(self, method: str):
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    body = json.loads(self.rfile.read(n) or b"{}") \
                        if n else {}
                except Exception as e:
                    return self._json(400, {"error": f"bad body: {e!r}"})
                try:
                    self._route(method, body)
                except Exception as e:
                    self._json(500, {"error": repr(e)})

            def _route(self, method: str, body):
                from ray_tpu.experimental.state import api as state
                from ray_tpu.job_submission import JobSubmissionClient
                path = self.path.split("?")[0]
                if path in ("/", "/index.html"):
                    from ray_tpu.dashboard.frontend import INDEX_HTML
                    return self._text(200, INDEX_HTML,
                                      ctype="text/html")
                if path == "/healthz":
                    return self._text(200, "ok")
                if path == "/metrics":
                    from ray_tpu.util import metrics
                    try:
                        text = metrics.prometheus_text()
                    except Exception:
                        text = ""
                    try:
                        text += _cluster_gauges(state)
                    except Exception:
                        pass
                    try:
                        text += _node_gauges(state)
                    except Exception:
                        pass
                    try:
                        text += _serve_gauges()
                    except Exception:
                        pass
                    try:
                        text += _slo_gauges()
                    except Exception:
                        pass
                    return self._text(200, text)
                if path == "/api/cluster_status":
                    return self._json(200, state.summarize_cluster())
                if path == "/api/nodes":
                    return self._json(200, {"nodes": state.list_nodes()})
                if path == "/api/nodes/stats":
                    return self._json(200,
                                      {"nodes": state.node_stats()})
                if path == "/api/actors":
                    return self._json(200,
                                      {"actors": state.list_actors()})
                if path == "/api/tasks":
                    from urllib.parse import parse_qs, urlparse
                    q = parse_qs(urlparse(self.path).query)

                    def one(k):
                        return (q.get(k) or [None])[0]
                    filters = {k: one(k) for k in
                               ("state", "name", "job_id", "node_id")
                               if one(k)}
                    page = state.list_tasks(
                        filters=filters or None,
                        page_size=int(one("limit") or 200),
                        continuation_token=one("token"))
                    return self._json(200, {
                        "tasks": list(page),
                        "next_token": page.next_token,
                        "total": page.total,
                        "dropped": page.dropped})
                if path == "/api/objects":
                    from urllib.parse import parse_qs, urlparse
                    q = parse_qs(urlparse(self.path).query)
                    page = state.list_objects(
                        page_size=int((q.get("limit") or ["200"])[0]),
                        continuation_token=(q.get("token") or [None])[0])
                    return self._json(200, {
                        "objects": list(page),
                        "next_token": page.next_token,
                        "total": page.total})
                if path == "/api/summary/tasks":
                    return self._json(200, state.summarize_tasks())
                if path == "/api/timeline":
                    from ray_tpu.util.timeline import (dump_dropped_total,
                                                       timeline_dump)
                    evs = timeline_dump()
                    return self._json(200, {
                        "events": evs,
                        "dropped": dump_dropped_total(evs)})
                if path == "/api/traces":
                    from urllib.parse import parse_qs, urlparse
                    q = parse_qs(urlparse(self.path).query)
                    page = state.list_traces(
                        page_size=int((q.get("limit") or ["100"])[0]),
                        continuation_token=(q.get("token")
                                            or [None])[0])
                    return self._json(200, {
                        "traces": list(page),
                        "next_token": page.next_token,
                        "total": page.total,
                        "dropped": page.dropped})
                m = re.match(r"^/api/trace/([^/]+)$", path)
                if m:
                    from ray_tpu._private import tracing
                    doc = state.get_trace(m.group(1))
                    spans = doc.get("spans") or []
                    doc["critical_path"] = tracing.critical_path(spans)
                    ok, detail = tracing.tree_complete(spans)
                    doc["complete"], doc["complete_detail"] = ok, detail
                    return self._json(200, doc)
                if path == "/api/serve/metrics":
                    from ray_tpu import serve as _serve
                    return self._json(200,
                                      {"deployments": _serve.metrics()})
                if path == "/api/gameday":
                    from ray_tpu.gameday import store as _gd_store
                    return self._json(200,
                                      {"report": _gd_store.load_report()})
                if path == "/api/placement_groups":
                    return self._json(
                        200, {"placement_groups":
                              state.list_placement_groups()})
                if path == "/api/profile/stacks":
                    from urllib.parse import parse_qs, urlparse
                    q = parse_qs(urlparse(self.path).query)
                    return self._json(200, state.profile_stacks(
                        node_id=(q.get("node_id") or [None])[0],
                        worker_id=(q.get("worker_id") or [None])[0]))
                if path == "/api/grafana/dashboards":
                    from ray_tpu.dashboard.grafana import (
                        generate_dashboards)
                    return self._json(200, generate_dashboards())
                if path == "/api/profile/flamegraph":
                    # timed sampling -> folded stacks (reference:
                    # reporter/profile_manager.py py-spy flamegraphs)
                    from urllib.parse import parse_qs, urlparse
                    q = parse_qs(urlparse(self.path).query)
                    return self._json(200, state.profile_flamegraph(
                        node_id=(q.get("node_id") or [None])[0],
                        worker_id=(q.get("worker_id") or [None])[0],
                        duration_s=float(
                            (q.get("duration_s") or ["2.0"])[0])))
                if path == "/api/events":
                    from urllib.parse import parse_qs, urlparse
                    q = parse_qs(urlparse(self.path).query)
                    return self._json(200, {"events":
                                            state.list_cluster_events(
                        limit=int(q.get("limit", ["200"])[0]),
                        severity=(q.get("severity") or [None])[0])})
                if path in ("/api/logs", "/api/logs/"):
                    return self._json(200, {"logs": state.list_logs()})
                m = re.match(r"^/api/logs/(.+)$", path)
                if m:
                    try:
                        return self._text(200, state.get_log(m.group(1)))
                    except (ValueError, OSError) as e:
                        return self._json(404, {"error": str(e)})
                if path == "/api/serve/applications":
                    from ray_tpu import serve as _serve
                    if method == "PUT":
                        # declarative deploy (reference: serve REST API,
                        # PUT /api/serve/applications/)
                        from ray_tpu.serve.schema import deploy_config
                        names = deploy_config(body or {})
                        return self._json(200, {"deployed": names})
                    return self._json(200, {
                        "applications": _serve.list_applications(),
                        "deployments": _serve.status()})
                client = JobSubmissionClient()
                if path in ("/api/jobs", "/api/jobs/"):
                    if method == "POST":
                        job_id = client.submit_job(
                            entrypoint=body["entrypoint"],
                            job_id=body.get("job_id"),
                            runtime_env=body.get("runtime_env"),
                            metadata=body.get("metadata"))
                        return self._json(200, {"job_id": job_id})
                    return self._json(200, {"jobs": client.list_jobs()})
                m = re.match(r"^/api/jobs/([^/]+)(/logs|/stop)?$", path)
                if m:
                    job_id, sub = m.group(1), m.group(2)
                    if sub == "/logs":
                        return self._json(
                            200, {"logs": client.get_job_logs(job_id)})
                    if sub == "/stop":
                        return self._json(
                            200, {"stopped": client.stop_job(job_id)})
                    info = client.get_job_info(job_id)
                    info["status"] = client.get_job_status(job_id)
                    return self._json(200, info)
                return self._json(404, {"error": f"no route {path}"})

        for attempt in range(32):
            try:
                self._server = ThreadingHTTPServer(
                    (self.host, self.port + attempt), Handler)
                self.port = self.port + attempt
                break
            except OSError:
                continue
        if self._server is None:
            raise RuntimeError("no free port for dashboard")
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()

    def get_port(self) -> int:
        return self.port

    def ping(self):
        return "pong"

    def shutdown(self):
        if self._server:
            self._server.shutdown()
        return "ok"


DASHBOARD_NAME = "DASHBOARD"


def _cluster_gauges(state) -> str:
    """Cluster-level gauges appended to /metrics (the native-metrics
    breadth the per-process registries can't see: node counts, resource
    totals, actor states — reference: the GCS-exported ray_* gauges)."""
    s = state.summarize_cluster()
    lines = []

    def g(name, value, help_):
        lines.append(f"# HELP ray_tpu_{name} {help_}")
        lines.append(f"# TYPE ray_tpu_{name} gauge")
        lines.append(f"ray_tpu_{name} {float(value)}")

    g("cluster_nodes_alive", s["nodes_alive"], "Alive nodes")
    g("cluster_nodes_total", s["nodes_total"], "All registered nodes")
    g("cluster_actors_alive", s["actors_alive"], "Alive actors")
    g("cluster_actors_total", s["actors_total"], "All actors")
    tasks = s.get("tasks") or {}
    for st, n in sorted((tasks.get("by_state") or {}).items()):
        lines.append(
            f'ray_tpu_cluster_tasks{{state="{st}"}} {float(n)}')
    g("cluster_task_table_dropped", tasks.get("dropped", 0),
      "Task records evicted past the bounded-table cap")
    for metric, key in (("cluster_resource_total", "cluster_resources"),
                        ("cluster_resource_available",
                         "available_resources")):
        for k, v in (s.get(key) or {}).items():
            if isinstance(v, (int, float)):
                lines.append(
                    f'ray_tpu_{metric}{{resource="{k}"}} {float(v)}')
    return "\n" + "\n".join(lines) + "\n"


def _node_gauges(state) -> str:
    """Per-node native metric set, labeled by node (reference:
    src/ray/stats/metric_defs.cc — ray_scheduler_tasks,
    ray_object_store_*, ray_spill_manager_*, and the reporter agent's
    node_cpu/node_mem gauges), scraped live from each raylet agent."""
    lines = []
    seen_help = set()

    def g(name, node, value, help_):
        full = f"ray_tpu_node_{name}"
        if full not in seen_help:
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} gauge")
            seen_help.add(full)
        lines.append(f'{full}{{node="{node}"}} {float(value)}')

    for n in state.node_stats():
        if "error" in n:
            continue
        nid = n["node_id"][:16]
        for k, v in n.get("physical", {}).items():
            g(k, nid, v, f"host {k.replace('_', ' ')}")
        sched = n.get("scheduler", {})
        for k in ("tasks_pending", "tasks_running",
                  "tasks_dispatched_total", "tasks_spilled_back_total",
                  "workers_alive", "workers_idle", "actors_alive",
                  "sched_native", "event_loop_lag_s",
                  "event_loop_lag_peak_s"):
            g(f"scheduler_{k}", nid, sched.get(k, 0), f"scheduler {k}")
        for res, v in (sched.get("resources_available") or {}).items():
            if isinstance(v, (int, float)):
                lines.append(
                    f'ray_tpu_node_resource_available'
                    f'{{node="{nid}",resource="{res}"}} {float(v)}')
        store = n.get("object_store", {})
        for k, v in store.items():
            if isinstance(v, (int, float)):
                g(f"object_store_{k}", nid, v, f"object store {k}")
        tpu = n.get("tpu", {})
        for k in ("num_chips", "chips_available"):
            g(f"tpu_{k}", nid, tpu.get(k, 0), f"TPU {k}")
    return "\n" + "\n".join(lines) + "\n" if lines else ""


def _serve_gauges() -> str:
    """Per-deployment serve data-plane gauges (queue depth, shed
    total/rate, p99/EWMA service time) from the controller's
    replica_load telemetry — the live serve panel, in exposition
    format. Empty when serve isn't running."""
    from ray_tpu import serve as _serve
    mets = _serve.metrics()
    if not mets:
        return ""
    lines = []
    seen_help = set()

    def g(name, dep, value, help_):
        full = f"ray_tpu_serve_{name}"
        if full not in seen_help:
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} gauge")
            seen_help.add(full)
        lines.append(f'{full}{{deployment="{dep}"}} {float(value)}')

    for dep, m in sorted(mets.items()):
        g("replicas", dep, m.get("replicas") or 0, "live replicas")
        g("queue_len", dep, m.get("queue_len") or 0,
          "queued + ongoing requests across replicas")
        g("shed_total", dep, m.get("shed_total") or 0,
          "requests shed (backpressure) total")
        g("shed_rate_per_s", dep, m.get("shed_rate_per_s") or 0,
          "shed rate since the previous scrape")
        g("requests_total", dep, m.get("requests_total") or 0,
          "requests admitted total")
        g("p99_seconds", dep, m.get("p99_s") or 0,
          "p99 service time over the replica latency reservoirs")
        g("ewma_seconds", dep, m.get("ewma_s") or 0,
          "EWMA service time (slowest replica)")
        llm = m.get("llm")
        if not isinstance(llm, dict):
            continue
        # LLM engine gauges (serve/llm): the autoscaler's signal set,
        # exported so capacity decisions are explainable from Grafana
        g("llm_tokens_per_s", dep, llm.get("tokens_per_s") or 0,
          "generated tokens/s across replica engines (5s window)")
        g("llm_kv_occupancy", dep, llm.get("kv_occupancy") or 0,
          "mean paged-KV pool occupancy across replicas (0..1)")
        g("llm_running_sequences", dep, llm.get("running") or 0,
          "sequences in the in-flight decode batches")
        g("llm_waiting_sequences", dep, llm.get("waiting") or 0,
          "sequences queued for admission")
        g("llm_generated_tokens_total", dep,
          llm.get("generated_tokens_total") or 0,
          "tokens generated since replica start")
        g("llm_ttft_p99_seconds", dep, llm.get("ttft_p99_s") or 0,
          "p99 time-to-first-token (worst replica reservoir)")
    return "\n" + "\n".join(lines) + "\n" if lines else ""


def _slo_gauges() -> str:
    """Client-side SLO gauges from the last published game-day report
    (``@gameday/report`` in the GCS KV) — the only exported metrics
    measured from the LOAD GENERATOR's side of the wire, labeled by
    scenario + phase. Empty when no game day has run."""
    from ray_tpu.gameday import store as gd_store
    report = gd_store.load_report()
    if not report:
        return ""
    scen = report.get("scenario", "unknown")
    lines = []
    seen_help = set()

    def g(name, labels, value, help_):
        full = f"ray_tpu_slo_{name}"
        if full not in seen_help:
            lines.append(f"# HELP {full} {help_}")
            lines.append(f"# TYPE {full} gauge")
            seen_help.add(full)
        lbl = ",".join(f'{k}="{v}"' for k, v in labels.items())
        lines.append(f"{full}{{{lbl}}} {float(value)}")

    phases = dict(report.get("phases") or {})
    phases["_overall"] = report.get("overall") or {}
    for phase, st in sorted(phases.items()):
        base = {"scenario": scen, "phase": phase}
        for outcome in ("admitted", "shed", "failed"):
            g("requests", {**base, "outcome": outcome},
              st.get(outcome) or 0,
              "client-observed request count by outcome")
        for q in ("p50", "p99", "p999"):
            g(f"latency_{q}_seconds", base,
              (st.get(f"{q}_ms") or 0.0) / 1e3,
              f"client-observed open-loop latency {q}")
    slo = report.get("slo") or {}
    g("error_budget_burn", {"scenario": scen, "slo": "availability"},
      slo.get("availability_burn") or 0.0,
      "error budget spent (1.0 = exhausted; -1 = zero-budget SLO)")
    if "latency_burn" in slo:
        g("error_budget_burn", {"scenario": scen, "slo": "latency"},
          slo.get("latency_burn") or 0.0,
          "error budget spent (1.0 = exhausted; -1 = zero-budget SLO)")
    recon = report.get("reconciliation") or {}
    g("reconcile_ok", {"scenario": scen},
      1.0 if recon.get("ok") else 0.0,
      "1 when the client ledger reconciled exactly with the "
      "server-side records")
    g("passed", {"scenario": scen},
      1.0 if report.get("passed") else 0.0,
      "1 when the scenario met its SLO and reconciled")
    return "\n" + "\n".join(lines) + "\n"


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> int:
    """Start (or find) the dashboard actor; returns the bound port."""
    import ray_tpu
    try:
        d = ray_tpu.get_actor(DASHBOARD_NAME)
        return ray_tpu.get(d.get_port.remote(), timeout=10.0)
    except Exception:
        pass
    cls = ray_tpu.remote(name=DASHBOARD_NAME, lifetime="detached",
                         max_concurrency=16)(DashboardActor)
    d = cls.remote(host, port)
    return ray_tpu.get(d.get_port.remote(), timeout=30.0)
