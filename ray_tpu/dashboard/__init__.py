"""ray_tpu.dashboard — HTTP observability + job REST
(reference: dashboard/)."""

from ray_tpu.dashboard.dashboard import (DASHBOARD_NAME, DashboardActor,
                                         start_dashboard)

__all__ = ["start_dashboard", "DashboardActor", "DASHBOARD_NAME"]
