"""Single-file dashboard frontend (reference: the dashboard/client React
app, scaled to a dependency-free page served by the same process). Polls
the REST endpoints: cluster status, nodes, serve metrics, tasks (paged +
state filter), actors, jobs, events, logs, and renders the task
timeline from the merged chrome-trace events.
"""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root { color-scheme: light dark; }
  body { font: 13px/1.45 system-ui, sans-serif; margin: 0;
         background: Canvas; color: CanvasText; }
  header { padding: 10px 16px; border-bottom: 1px solid color-mix(in srgb,
           CanvasText 18%, transparent); display: flex; gap: 16px;
           align-items: baseline; }
  header h1 { font-size: 15px; margin: 0; }
  header .muted { opacity: .65; }
  main { padding: 12px 16px; display: grid; gap: 14px; }
  section h2 { font-size: 13px; margin: 0 0 6px;
               text-transform: uppercase; letter-spacing: .06em;
               opacity: .75; }
  .tiles { display: flex; gap: 10px; flex-wrap: wrap; }
  .tile { border: 1px solid color-mix(in srgb, CanvasText 18%,
          transparent); border-radius: 8px; padding: 8px 14px;
          min-width: 110px; }
  .tile b { display: block; font-size: 20px; }
  table { border-collapse: collapse; width: 100%; }
  th, td { text-align: left; padding: 3px 10px 3px 0; border-bottom:
           1px solid color-mix(in srgb, CanvasText 10%, transparent);
           font-variant-numeric: tabular-nums; white-space: nowrap; }
  th { opacity: .7; font-weight: 600; }
  td.msg { white-space: normal; }
  .sev-ERROR, .sev-FATAL, .st-FAILED { color: #c62828; font-weight: 600; }
  .sev-WARNING { color: #b26a00; font-weight: 600; }
  .st-FINISHED { color: #2e7d32; }
  .st-RUNNING { color: #1565c0; font-weight: 600; }
  pre { background: color-mix(in srgb, CanvasText 6%, transparent);
        padding: 8px; border-radius: 6px; max-height: 320px;
        overflow: auto; }
  a { color: inherit; }
  select, button { font: inherit; }
  .bar-row { display: flex; align-items: center; height: 14px; }
  .bar-label { width: 180px; flex: none; overflow: hidden;
               text-overflow: ellipsis; opacity: .7; font-size: 11px; }
  .bar-lane { position: relative; flex: 1; height: 12px; }
  .bar { position: absolute; height: 10px; top: 1px; border-radius: 2px;
         background: #1565c0; min-width: 2px; opacity: .85; }
  .bar.failed { background: #c62828; }
  #timeline { max-height: 420px; overflow: auto; border: 1px solid
              color-mix(in srgb, CanvasText 12%, transparent);
              border-radius: 6px; padding: 6px; }
  .muted { opacity: .65; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="muted" id="updated"></span>
</header>
<main>
  <section><h2>Cluster</h2><div class="tiles" id="tiles"></div></section>
  <section><h2>Serve</h2><table id="serve"></table>
    <div class="muted" id="serve-empty"></div></section>
  <section><h2>Game day</h2>
    <div class="tiles" id="gd-tiles"></div>
    <table id="gameday"></table>
    <div class="muted" id="gd-empty"></div></section>
  <section><h2>Nodes</h2><table id="nodes"></table></section>
  <section>
    <h2>Tasks</h2>
    <div style="margin-bottom:6px">
      state: <select id="taskstate">
        <option value="">(all)</option>
        <option>PENDING_SCHEDULING</option>
        <option>PENDING_NODE_ASSIGNMENT</option>
        <option>RUNNING</option>
        <option>FINISHED</option>
        <option>FAILED</option>
      </select>
      <span class="muted" id="taskmeta"></span>
    </div>
    <table id="tasks"></table>
  </section>
  <section>
    <h2>Task timeline</h2>
    <button id="tl-load">load timeline</button>
    <span class="muted" id="tl-meta"></span>
    <div id="timeline"></div>
  </section>
  <section>
    <h2>Traces</h2>
    <div style="margin-bottom:6px">
      <button id="tr-load">load traces</button>
      <input id="tr-id" placeholder="trace id" size="20">
      <button id="tr-show">waterfall</button>
      <span class="muted" id="tr-meta"></span>
    </div>
    <table id="traces"></table>
    <div class="muted" id="tr-cp"></div>
    <div id="waterfall"></div>
  </section>
  <section><h2>Actors</h2><table id="actors"></table></section>
  <section><h2>Jobs</h2><table id="jobs"></table></section>
  <section><h2>Events</h2><table id="events"></table></section>
  <section>
    <h2>Logs</h2>
    <select id="logsel"></select>
    <pre id="logview">(select a log)</pre>
  </section>
</main>
<script>
const get = async p => (await fetch(p)).json();
const esc = s => String(s).replace(/[&<>]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;"}[c]));
const row = cells => "<tr>" + cells.map(c => "<td" +
  (c && c.cls ? ` class="${c.cls}"` : "") + ">" +
  esc(c && c.v !== undefined ? c.v : c) + "</td>").join("") + "</tr>";
const head = cols => "<tr>" + cols.map(c => `<th>${c}</th>`).join("")
  + "</tr>";
const ms = s => s == null ? "-" : (s * 1000).toFixed(1) + "ms";

async function refresh() {
  try {
    const s = await get("/api/cluster_status");
    const res = s.cluster_resources || {};
    const t = s.tasks || {};
    const by = t.by_state || {};
    document.getElementById("tiles").innerHTML = [
      ["nodes alive", s.nodes_alive + "/" + s.nodes_total],
      ["actors alive", s.actors_alive + "/" + s.actors_total],
      ["CPU", res.CPU ?? 0], ["TPU", res.TPU ?? 0],
      ["tasks running", by.RUNNING ?? 0],
      ["tasks finished", by.FINISHED ?? 0],
      ["tasks failed", by.FAILED ?? 0],
    ].map(([k, v]) => `<div class="tile"><b>${esc(v)}</b>${esc(k)}
      </div>`).join("");

    const serve = (await get("/api/serve/metrics")).deployments || {};
    const deps = Object.entries(serve);
    document.getElementById("serve-empty").textContent =
      deps.length ? "" : "(no serve deployments)";
    document.getElementById("serve").innerHTML = !deps.length ? "" :
      head(["deployment", "status", "replicas", "queue depth",
            "shed total", "shed/s", "requests", "p99", "ewma"]) +
      deps.map(([n, m]) => row([n, m.status,
        (m.replicas ?? 0) + "/" + (m.target_replicas ?? 0),
        m.queue_len ?? 0, m.shed_total ?? 0, m.shed_rate_per_s ?? 0,
        m.requests_total ?? 0, ms(m.p99_s), ms(m.ewma_s)])).join("");

    // last published game-day report: client-side SLO truth (open-loop
    // p50/p99/p99.9 per phase, ledger counts, budget burn) + the
    // reconciliation verdict against the server-side records
    const gd = (await get("/api/gameday")).report;
    document.getElementById("gd-empty").textContent =
      gd ? "" : "(no game day has run — ray-tpu gameday run <scenario>)";
    if (gd) {
      const recon = gd.reconciliation || {};
      const slo = gd.slo || {};
      const o = gd.overall || {};
      document.getElementById("gd-tiles").innerHTML = [
        ["scenario", gd.scenario + " @ seed " + gd.seed],
        ["verdict", gd.passed ? "PASSED" : "FAILED"],
        ["reconciled", recon.ok ? "yes" : "NO"],
        ["failed requests", o.failed ?? "-"],
        ["shed", o.shed ?? "-"],
        ["budget burn", (slo.availability_burn ?? 0).toFixed(3)],
      ].map(([k, v]) => `<div class="tile"><b>${esc(v)}</b>${esc(k)}
        </div>`).join("");
      const phases = Object.entries(gd.phases || {});
      document.getElementById("gameday").innerHTML = !phases.length ? "" :
        head(["phase", "total", "admitted", "shed", "failed", "p50",
              "p99", "p99.9", "max"]) +
        phases.map(([n, p]) => row([n, p.total, p.admitted, p.shed,
          {v: p.failed, cls: p.failed ? "st-FAILED" : ""},
          p.p50_ms + "ms", p.p99_ms + "ms", p.p999_ms + "ms",
          p.max_ms + "ms"])).join("");
    } else {
      document.getElementById("gd-tiles").innerHTML = "";
      document.getElementById("gameday").innerHTML = "";
    }

    const nodes = (await get("/api/nodes")).nodes || [];
    const stats = (await get("/api/nodes/stats")).nodes || [];
    const byId = Object.fromEntries(stats.map(s => [s.node_id, s]));
    const gb = b => (b / 1e9).toFixed(1) + "G";
    document.getElementById("nodes").innerHTML =
      head(["node", "alive", "cpu%", "mem free", "store used",
            "tasks p/r", "workers", "spilled", "resources"]) +
      nodes.map(n => { const s = byId[n.node_id] || {};
        const p = s.physical || {}, sc = s.scheduler || {},
              os_ = s.object_store || {};
        return row([n.node_id.slice(0, 12), n.alive,
          p.cpu_percent != null ? p.cpu_percent.toFixed(0) : "-",
          p.mem_available_bytes != null ? gb(p.mem_available_bytes) : "-",
          os_.used_bytes != null ?
            gb(os_.used_bytes) + "/" + gb(os_.capacity) : "-",
          (sc.tasks_pending ?? "-") + "/" + (sc.tasks_running ?? "-"),
          sc.workers_alive ?? "-",
          os_.spilled_objects ?? "-",
          JSON.stringify(n.resources)]); }).join("");

    const st = document.getElementById("taskstate").value;
    const td = await get("/api/tasks?limit=100" +
                         (st ? "&state=" + st : ""));
    const tasks = td.tasks || [];
    document.getElementById("taskmeta").textContent =
      `${tasks.length} of ${td.total ?? "?"} shown` +
      (td.dropped ? ` · ${td.dropped} evicted (table cap)` : "");
    document.getElementById("tasks").innerHTML =
      head(["task", "name", "state", "attempt", "node", "pid",
            "duration", "error"]) +
      tasks.map(x => row([x.task_id.slice(0, 12), x.name || "-",
        {v: x.state, cls: "st-" + x.state}, x.attempt || 0,
        (x.node_id || "").slice(0, 8) || "-", x.worker_pid ?? "-",
        x.duration_s != null ? ms(x.duration_s) : "-",
        {v: x.error || "", cls: "msg"}])).join("");

    const actors = (await get("/api/actors")).actors || [];
    document.getElementById("actors").innerHTML =
      head(["actor", "class", "state", "restarts"]) +
      actors.map(a => row([(a.actor_id || "").slice(0, 12),
        a.class_name, a.state, a.num_restarts || 0])).join("");

    const jobs = (await get("/api/jobs")).jobs || [];
    document.getElementById("jobs").innerHTML =
      head(["job", "status", "entrypoint"]) +
      jobs.map(j => row([j.job_id, j.status,
        (j.entrypoint || "").slice(0, 90)])).join("");

    const events = (await get("/api/events?limit=50")).events || [];
    document.getElementById("events").innerHTML =
      head(["time", "severity", "source", "label", "message"]) +
      events.slice().reverse().map(e => row([
        new Date(e.timestamp * 1000).toLocaleTimeString(),
        {v: e.severity, cls: "sev-" + e.severity}, e.source, e.label,
        {v: e.message, cls: "msg"}])).join("");

    const sel = document.getElementById("logsel");
    if (!sel.dataset.loaded) {
      const logs = (await get("/api/logs")).logs || [];
      sel.innerHTML = "<option value=''>(select a log)</option>" +
        logs.map(l => `<option>${esc(l)}</option>`).join("");
      sel.dataset.loaded = "1";
      sel.onchange = async () => {
        if (!sel.value) return;
        const r = await fetch("/api/logs/" +
                              encodeURIComponent(sel.value));
        document.getElementById("logview").textContent = await r.text();
      };
    }
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("updated").textContent = "error: " + e;
  }
}

// Task timeline: the merged chrome-trace ('X' complete events, one
// lane per pid:tid), rendered as proportional bars. On demand — the
// trace merge walks every process's buffer.
async function loadTimeline() {
  const box = document.getElementById("timeline");
  box.innerHTML = "loading…";
  try {
    const evs = ((await get("/api/timeline")).events || [])
      .filter(e => e.ph === "X" && e.dur != null);
    if (!evs.length) { box.innerHTML = "(no task events yet)"; return; }
    evs.sort((a, b) => a.ts - b.ts);
    const shown = evs.slice(-1000);
    const t0 = Math.min(...shown.map(e => e.ts));
    const t1 = Math.max(...shown.map(e => e.ts + e.dur));
    const span = Math.max(t1 - t0, 1);
    document.getElementById("tl-meta").textContent =
      `${shown.length}${evs.length > shown.length ? " (latest) of " +
        evs.length : ""} tasks · ${(span / 1e6).toFixed(2)}s window`;
    const lanes = new Map();
    for (const e of shown) {
      const key = `pid ${e.pid} · tid ${e.tid}`;
      if (!lanes.has(key)) lanes.set(key, []);
      lanes.get(key).push(e);
    }
    box.innerHTML = [...lanes.entries()].map(([key, es]) =>
      `<div class="bar-row"><div class="bar-label">${esc(key)}</div>` +
      `<div class="bar-lane">` + es.map(e =>
        `<div class="bar${e.cname === "terrible" ? " failed" : ""}"` +
        ` style="left:${(100 * (e.ts - t0) / span).toFixed(3)}%;` +
        `width:${(100 * e.dur / span).toFixed(3)}%"` +
        ` title="${esc(e.name)} ${(e.dur / 1000).toFixed(2)}ms"></div>`
      ).join("") + `</div></div>`).join("");
  } catch (e) { box.innerHTML = "error: " + esc(e); }
}
document.getElementById("tl-load").onclick = loadTimeline;

// Distributed traces: summaries table + per-trace waterfall (one row
// per span, indented by tree depth, colored by attributed phase) with
// the critical-path phase table from /api/trace/<id>.
const PHASE_COLORS = {queue: "#b26a00", schedule: "#6a1b9a",
  dispatch: "#00838f", transfer: "#546e7a", execute: "#1565c0",
  deserialize: "#2e7d32", submit: "#9e9d24", other: "#757575"};
async function loadTraces() {
  const meta = document.getElementById("tr-meta");
  try {
    const d = await get("/api/traces?limit=50");
    const traces = (d.traces || []).sort(
      (a, b) => (b.start_ts || 0) - (a.start_ts || 0));
    meta.textContent = `${traces.length} of ${d.total ?? "?"} shown` +
      (d.dropped ? ` · ${d.dropped} spans evicted` : "");
    document.getElementById("traces").innerHTML =
      head(["trace", "root", "spans", "start", "duration", "status"]) +
      traces.map(t => row([t.trace_id, t.root || "-", t.spans,
        t.start_ts ? new Date(t.start_ts * 1000).toLocaleTimeString()
                   : "-",
        t.duration_s != null ? ms(t.duration_s) : "-",
        {v: t.status, cls: t.status === "error" ? "st-FAILED" : ""}
      ])).join("");
    document.getElementById("traces").onclick = e => {
      const tr = e.target.closest("tr");
      if (tr && tr.cells.length && tr.cells[0].textContent !== "trace") {
        document.getElementById("tr-id").value =
          tr.cells[0].textContent;
        showWaterfall();
      }
    };
  } catch (e) { meta.textContent = "error: " + e; }
}
async function showWaterfall() {
  const id = document.getElementById("tr-id").value.trim();
  const box = document.getElementById("waterfall");
  const cpBox = document.getElementById("tr-cp");
  if (!id) { box.innerHTML = "(enter a trace id)"; return; }
  box.innerHTML = "loading…";
  try {
    const doc = await get("/api/trace/" + encodeURIComponent(id));
    const spans = (doc.spans || []).filter(
      s => s.start_ts != null && s.end_ts != null);
    if (!spans.length) { box.innerHTML = "(no spans)"; return; }
    const cp = doc.critical_path || {};
    cpBox.textContent = `critical path: ` +
      Object.entries(cp.phases || {}).map(([k, v]) =>
        `${k} ${(v * 1e3).toFixed(1)}ms`).join(" · ") +
      ` — ${((cp.attributed_frac || 0) * 100).toFixed(1)}% attributed` +
      (doc.complete ? "" : ` · INCOMPLETE: ${doc.complete_detail}`);
    const ids = new Set(spans.map(s => s.span_id));
    const depth = s => { let d = 0, cur = s;
      const byId = Object.fromEntries(spans.map(x => [x.span_id, x]));
      while (cur && ids.has(cur.parent_span_id) && d < 32) {
        cur = byId[cur.parent_span_id]; d++; } return d; };
    const t0 = Math.min(...spans.map(s => s.start_ts));
    const t1 = Math.max(...spans.map(s => s.end_ts));
    const span = Math.max(t1 - t0, 1e-6);
    box.innerHTML = spans.slice().sort((a, b) =>
      a.start_ts - b.start_ts || depth(a) - depth(b)).map(s => {
      const d = depth(s);
      return `<div class="bar-row">` +
        `<div class="bar-label" style="padding-left:${d * 10}px"` +
        ` title="${esc(s.name)}">${esc(s.name)}</div>` +
        `<div class="bar-lane"><div class="bar` +
        `${s.status === "error" ? " failed" : ""}"` +
        ` style="left:${(100 * (s.start_ts - t0) / span).toFixed(3)}%;` +
        `width:${Math.max(100 * (s.end_ts - s.start_ts) / span, .15)
          .toFixed(3)}%;` +
        `background:${PHASE_COLORS[s.phase] || PHASE_COLORS.other}"` +
        ` title="${esc(s.name)} ${((s.end_ts - s.start_ts) * 1e3)
          .toFixed(2)}ms (${esc(s.phase || "?")})"></div></div></div>`;
    }).join("");
  } catch (e) { box.innerHTML = "error: " + esc(e); }
}
document.getElementById("tr-load").onclick = loadTraces;
document.getElementById("tr-show").onclick = showWaterfall;
document.getElementById("taskstate").onchange = refresh;
refresh();
setInterval(refresh, 4000);
</script>
</body>
</html>
"""
