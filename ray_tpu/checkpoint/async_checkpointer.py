"""AsyncCheckpointer: snapshot device shards to host, write in background.

The train step blocks only for the host snapshot (device→host memcpy of
the shards this process *owns*); serialization, checksumming, fsync and
commit happen on a single background writer thread. One save may be in
flight at a time — a second ``save()`` blocks until the first lands
(backpressure, counted in the save's ``blocked_ms``) so checkpoints can
never consume unbounded host memory or reorder on disk.

Dedup of replicated state (orbax-style): a leaf's addressable shards are
written only where ``replica_id == 0``, and host-resident (unsharded)
leaves are written only by process 0 — instead of every host writing full
copies of the entire replicated tree.

Env knobs:
  RTPU_CKPT_ASYNC=0   write inline on the calling thread (the sync
                      baseline; also what the _BENCH_CKPT=1 bench compares
                      against)
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.checkpoint.manager import CheckpointManager, PendingCheckpoint

logger = logging.getLogger(__name__)


def _async_enabled() -> bool:
    return os.environ.get("RTPU_CKPT_ASYNC", "1") != "0"


def sanitize_key(key: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", key) or "leaf"


@dataclass
class SaveStats:
    """Per-save accounting. ``blocked_ms`` is the time the *training*
    thread spent inside save() — backpressure wait + host snapshot;
    write/commit happen off-thread (or inline in sync mode, where they
    count toward blocked_ms too)."""

    step: int
    snapshot_ms: float = 0.0
    backpressure_ms: float = 0.0
    blocked_ms: float = 0.0
    write_ms: float = 0.0
    commit_ms: float = 0.0
    bytes: int = 0
    files: int = 0
    committed: bool = False
    error: Optional[str] = None

    def as_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in (
            "step", "snapshot_ms", "backpressure_ms", "blocked_ms",
            "write_ms", "commit_ms", "bytes", "files", "committed",
            "error")}


def snapshot_to_host(state, process_index: int = 0) -> List[Dict[str, Any]]:
    """Flatten a pytree into host-memory shard entries, deduplicating
    replicas. Copies (never aliases) device buffers so donated/reused
    buffers can't corrupt an in-flight save. Returns entries shaped like
    the on-disk per-process manifest: {key, data, index, shape, dtype}."""
    import numpy as np
    from jax.tree_util import tree_flatten_with_path

    from ray_tpu.air.checkpoint import _index_to_json

    leaves, _ = tree_flatten_with_path(state)
    entries: List[Dict[str, Any]] = []
    for path, leaf in leaves:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        if hasattr(leaf, "addressable_shards"):
            for shard in leaf.addressable_shards:
                if getattr(shard, "replica_id", 0) != 0:
                    continue  # replica owned by another shard/process
                entries.append({
                    "key": key,
                    "data": np.array(shard.data, copy=True),
                    "index": _index_to_json(shard.index),
                    "shape": list(leaf.shape),
                    "dtype": str(leaf.dtype)})
        else:
            if process_index != 0:
                continue  # host-replicated leaf: only process 0 writes
            arr = np.array(leaf, copy=True)
            entries.append({"key": key, "data": arr, "index": None,
                            "shape": list(arr.shape),
                            "dtype": str(arr.dtype)})
    return entries


def write_host_snapshot(pdir: str, entries: List[Dict[str, Any]]) -> int:
    """Write snapshot entries into one process dir with deterministic
    ``key__shard<i>.npy`` names + a per-process manifest.json (the schema
    ShardedCheckpoint.restore reassembles from). Returns bytes written."""
    import json
    import shutil

    import numpy as np

    # this process owns pdir exclusively: clear debris a previous attempt
    # at the same step may have left (restart after a mid-save death)
    if os.path.isdir(pdir):
        shutil.rmtree(pdir)
    os.makedirs(pdir, exist_ok=True)
    manifest = []
    shard_counts: Dict[str, int] = {}
    nbytes = 0
    for e in entries:
        san = sanitize_key(e["key"])
        i = shard_counts.get(san, 0)
        shard_counts[san] = i + 1
        fname = f"{san}__shard{i}.npy" if e["index"] is not None \
            else f"{san}__full.npy"
        if e["index"] is None and i:
            fname = f"{san}__full{i}.npy"  # sanitization collision
        np.save(os.path.join(pdir, fname), e["data"])
        nbytes += e["data"].nbytes
        manifest.append({"key": e["key"], "file": fname,
                         "index": e["index"], "shape": e["shape"],
                         "dtype": e["dtype"]})
    part = os.path.join(pdir, ".manifest.json.part")
    with open(part, "w") as f:
        json.dump(manifest, f)
    os.replace(part, os.path.join(pdir, "manifest.json"))
    return nbytes


class AsyncCheckpointer:
    """Background sharded saver bound to one CheckpointManager.

    commit semantics:
      - ``commit="auto"`` (default): the writer thread commits iff this is
        a single-process save (process_count == 1). Gangs leave commit to
        the driver, which owns the all-ranks round barrier.
      - ``commit=True`` / ``commit=False`` force it.
    """

    def __init__(self, manager: CheckpointManager, *,
                 process_index: int = 0, process_count: int = 1,
                 commit: Any = "auto"):
        self.manager = manager
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        if commit == "auto":
            commit = process_count == 1
        self._commit = bool(commit)
        self._stats: List[SaveStats] = []
        self._cond = threading.Condition()
        self._inflight: Optional[tuple] = None  # (step, entries, stats)
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------------- save

    def save(self, step: int, state,
             metadata: Optional[Dict[str, Any]] = None) -> PendingCheckpoint:
        """Snapshot ``state`` to host and hand off to the writer. Blocks
        only for (a) a previous save still in flight and (b) the host
        snapshot itself. Raises if the previous save failed."""
        t0 = time.perf_counter()
        stats = SaveStats(step=step)
        with self._cond:
            while self._inflight is not None and self._error is None:
                self._cond.wait(timeout=0.5)
            self._raise_on_error()
        stats.backpressure_ms = (time.perf_counter() - t0) * 1e3
        t1 = time.perf_counter()
        entries = snapshot_to_host(state, self.process_index)
        stats.snapshot_ms = (time.perf_counter() - t1) * 1e3
        if _async_enabled():
            with self._cond:
                self._ensure_thread()
                self._inflight = (step, entries, metadata, stats)
                self._cond.notify_all()
            stats.blocked_ms = (time.perf_counter() - t0) * 1e3
        else:
            self._write_one(step, entries, metadata, stats)
            stats.blocked_ms = (time.perf_counter() - t0) * 1e3
            self._raise_on_error()
        self._stats.append(stats)
        return PendingCheckpoint(step)

    def wait(self):
        """Barrier: block until the in-flight save (if any) fully landed;
        re-raise a writer failure."""
        with self._cond:
            while self._inflight is not None and self._error is None:
                self._cond.wait(timeout=0.5)
            self._raise_on_error()

    def finalize(self):
        """wait() + stop the writer thread. The checkpointer is reusable
        afterwards (a new save restarts the thread)."""
        self.wait()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        self._stop = False

    # --------------------------------------------------------------- stats

    @property
    def stats(self) -> List[SaveStats]:
        return list(self._stats)

    def stats_summary(self) -> Dict[str, Any]:
        done = [s for s in self._stats if s.error is None]
        if not done:
            return {"saves": 0}
        return {
            "saves": len(done),
            "blocked_ms_mean": sum(s.blocked_ms for s in done) / len(done),
            "snapshot_ms_mean": sum(s.snapshot_ms for s in done) / len(done),
            "write_ms_mean": sum(s.write_ms for s in done) / len(done),
            "bytes_total": sum(s.bytes for s in done),
        }

    # -------------------------------------------------------------- writer

    def _raise_on_error(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                f"async checkpoint save failed: {err!r}") from err

    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, daemon=True,
                name=f"rtpu-ckpt-writer-p{self.process_index}")
            self._thread.start()

    def _writer_loop(self):
        while True:
            with self._cond:
                while self._inflight is None and not self._stop:
                    self._cond.wait(timeout=0.5)
                if self._stop:
                    return
                step, entries, metadata, stats = self._inflight
            try:
                self._write_one(step, entries, metadata, stats)
            finally:
                with self._cond:
                    self._inflight = None
                    self._cond.notify_all()

    def _write_one(self, step, entries, metadata, stats: SaveStats):
        try:
            t0 = time.perf_counter()
            tmp = self.manager.begin_step(step)
            pdir = os.path.join(tmp, f"process_{self.process_index}")
            stats.bytes = write_host_snapshot(pdir, entries)
            stats.files = len(entries)
            stats.write_ms = (time.perf_counter() - t0) * 1e3
            if self._commit:
                t1 = time.perf_counter()
                self.manager.commit_step(step, metadata=metadata)
                stats.commit_ms = (time.perf_counter() - t1) * 1e3
                stats.committed = True
        except BaseException as e:  # surfaced on the next save()/wait()
            stats.error = repr(e)
            with self._cond:
                self._error = e
            logger.warning("checkpoint step %d write failed: %r", step, e)
