"""CheckpointManager: a step-numbered checkpoint root with atomic commit.

Layout (one directory per training run)::

    root/
      step_00000003/          committed step: COMMIT marker present
        MANIFEST.json         {relpath: {bytes, crc32}} for every file
        COMMIT                written last — presence == durably committed
        checkpoint.pkl        (driver-staged dict checkpoints)
        process_0/            (sharded saves: one subdir per process)
          key__shard0.npy ...
          manifest.json       per-process shard manifest
      tmp_step_00000004/      in-flight or abandoned save — never restored

Commit protocol (``commit_step``): checksum + fsync every file under the
tmp dir, write MANIFEST.json, fsync it and the tmp dir, ``os.rename`` the
tmp dir to ``step_N/`` (atomic on POSIX), then write + fsync the COMMIT
marker and fsync the root. A crash at any point leaves either the previous
committed step intact and a garbage ``tmp_step_N/``, or a ``step_N/``
without COMMIT — both are skipped by ``latest_committed()`` and reaped by
retention. Restore therefore never sees a torn checkpoint.

Env knobs:
  RTPU_CKPT_FSYNC=0   skip fsyncs (tests/benchmarks on tmpfs)
  RTPU_CKPT_VERIFY=1  re-verify per-file checksums when resolving
                      latest_committed() / load()
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

MANIFEST_NAME = "MANIFEST.json"
COMMIT_MARKER = "COMMIT"
_STEP_PREFIX = "step_"
_TMP_PREFIX = "tmp_step_"


def _fsync_enabled() -> bool:
    return os.environ.get("RTPU_CKPT_FSYNC", "1") != "0"


def _verify_enabled() -> bool:
    return os.environ.get("RTPU_CKPT_VERIFY", "0") == "1"


def fsync_file(path: str):
    if not _fsync_enabled():
        return
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str):
    if not _fsync_enabled():
        return
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


class PendingCheckpoint:
    """Marker for a step staged under the manager but not yet committed.

    Rides in ``TrainingResult.checkpoint`` from workers to the driver; the
    driver (which sees the whole-gang round barrier) seals the step with
    ``CheckpointManager.commit_step``. Tiny and picklable by design.
    """

    __slots__ = ("step",)

    def __init__(self, step: int):
        self.step = int(step)

    def __repr__(self):
        return f"PendingCheckpoint(step={self.step})"


class CheckpointManager:
    """Owns one checkpoint root: staging, atomic commit, retention,
    committed-step resolution. Safe for many writer processes on a shared
    filesystem as long as a single process calls ``commit_step`` (the
    driver / rank 0)."""

    def __init__(self, root: str, *, num_to_keep: Optional[int] = None,
                 keep_every_k: int = 0, checkpoint_config=None):
        if checkpoint_config is not None:
            num_to_keep = checkpoint_config.num_to_keep
            keep_every_k = getattr(checkpoint_config, "keep_every_k", 0) or 0
        self.root = os.path.abspath(os.path.expanduser(root))
        self.num_to_keep = num_to_keep
        self.keep_every_k = int(keep_every_k or 0)
        os.makedirs(self.root, exist_ok=True)

    # --------------------------------------------------------------- naming

    def step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"{_STEP_PREFIX}{step:08d}")

    def tmp_dir(self, step: int) -> str:
        return os.path.join(self.root, f"{_TMP_PREFIX}{step:08d}")

    @staticmethod
    def _parse_step(name: str, prefix: str = _STEP_PREFIX) -> Optional[int]:
        if not name.startswith(prefix):
            return None
        try:
            return int(name[len(prefix):])
        except ValueError:
            return None

    # -------------------------------------------------------------- staging

    def begin_step(self, step: int) -> str:
        """Create (or join) the in-flight dir for ``step``. Every writer
        process of a gang calls this and drops its files underneath."""
        tmp = self.tmp_dir(step)
        os.makedirs(tmp, exist_ok=True)
        return tmp

    def stage(self, step: int, checkpoint) -> str:
        """Materialize an ``air.Checkpoint`` payload into the in-flight
        dir. Dict checkpoints become ``checkpoint.pkl`` (written via a
        temp file so a torn write can't masquerade as a payload);
        directory checkpoints are copied in wholesale."""
        tmp = self.begin_step(step)
        data = getattr(checkpoint, "_data", None)
        src = getattr(checkpoint, "_dir", None)
        if data is not None:
            part = os.path.join(tmp, ".checkpoint.pkl.part")
            with open(part, "wb") as f:
                pickle.dump(data, f, protocol=5)
            os.replace(part, os.path.join(tmp, "checkpoint.pkl"))
        elif src is not None:
            shutil.copytree(src, tmp, dirs_exist_ok=True)
        else:
            raise TypeError(f"cannot stage {checkpoint!r}: "
                            "not an air.Checkpoint")
        return tmp

    # --------------------------------------------------------------- commit

    def commit_step(self, step: int,
                    metadata: Optional[Dict[str, Any]] = None) -> str:
        """Seal ``step``: checksum + fsync everything staged under the tmp
        dir, write the manifest, atomically rename, mark committed, then
        apply retention. Returns the committed directory."""
        tmp = self.tmp_dir(step)
        if not os.path.isdir(tmp):
            raise FileNotFoundError(
                f"no staged checkpoint for step {step} at {tmp}")
        files: Dict[str, Dict[str, Any]] = {}
        for dirpath, _dirnames, filenames in os.walk(tmp):
            for fname in filenames:
                fpath = os.path.join(dirpath, fname)
                rel = os.path.relpath(fpath, tmp)
                files[rel] = {"bytes": os.path.getsize(fpath),
                              "crc32": crc32_file(fpath)}
                fsync_file(fpath)
        manifest = {"format": 1, "step": step, "files": files,
                    "committed_unix": time.time(),
                    "meta": dict(metadata or {})}
        mpath = os.path.join(tmp, MANIFEST_NAME)
        with open(mpath, "w") as f:
            json.dump(manifest, f, indent=1)
        fsync_file(mpath)
        fsync_dir(tmp)
        final = self.step_dir(step)
        if os.path.exists(final):
            # a prior attempt died between rename and COMMIT — reclaim
            shutil.rmtree(final)
        os.rename(tmp, final)
        marker = os.path.join(final, COMMIT_MARKER)
        with open(marker, "w") as f:
            json.dump({"step": step, "unix": time.time()}, f)
        fsync_file(marker)
        fsync_dir(final)
        fsync_dir(self.root)
        self._apply_retention()
        return final

    def is_committed(self, step: int) -> bool:
        return os.path.exists(os.path.join(self.step_dir(step),
                                           COMMIT_MARKER))

    # ------------------------------------------------------------ resolution

    def committed_steps(self) -> List[int]:
        out = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for name in names:
            step = self._parse_step(name)
            if step is not None and self.is_committed(step):
                out.append(step)
        return sorted(out)

    def latest_committed(self, verify: Optional[bool] = None
                         ) -> Optional[int]:
        """Newest committed step, skipping partial (no COMMIT) and — when
        verification is on — corrupt (checksum-mismatch) steps."""
        if verify is None:
            verify = _verify_enabled()
        for step in reversed(self.committed_steps()):
            if not verify or self.verify_step(step):
                return step
        return None

    def verify_step(self, step: int) -> bool:
        """Check every manifest entry exists with matching size + crc32."""
        sdir = self.step_dir(step)
        mpath = os.path.join(sdir, MANIFEST_NAME)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            for rel, ent in manifest.get("files", {}).items():
                fpath = os.path.join(sdir, rel)
                if os.path.getsize(fpath) != ent["bytes"]:
                    logger.warning("checkpoint step %d: size mismatch on %s",
                                   step, rel)
                    return False
                if crc32_file(fpath) != ent["crc32"]:
                    logger.warning("checkpoint step %d: crc mismatch on %s",
                                   step, rel)
                    return False
            return True
        except (OSError, ValueError, KeyError) as e:
            logger.warning("checkpoint step %d unreadable: %s", step, e)
            return False

    def load(self, step: Optional[int] = None):
        """A directory-backed ``air.Checkpoint`` for a committed step
        (default: latest). Raises FileNotFoundError when nothing committed
        or the requested step is partial/corrupt."""
        from ray_tpu.air.checkpoint import Checkpoint
        if step is None:
            step = self.latest_committed()
            if step is None:
                raise FileNotFoundError(
                    f"no committed checkpoint under {self.root}")
        if not self.is_committed(step):
            raise FileNotFoundError(
                f"step {step} is not committed under {self.root}")
        if _verify_enabled() and not self.verify_step(step):
            raise FileNotFoundError(
                f"step {step} failed checksum verification")
        return Checkpoint.from_directory(self.step_dir(step))

    def restore_state(self, target_state, step: Optional[int] = None):
        """Reassemble a sharded train state onto ``target_state``'s
        shardings — works across a different process count / mesh than the
        one that saved (shards are indexed by global slices, not ranks)."""
        from ray_tpu.air.checkpoint import ShardedCheckpoint
        ckpt = self.load(step)
        return ShardedCheckpoint(ckpt._dir).restore(target_state)

    # ------------------------------------------------------------- retention

    def delete_step(self, step: int):
        sdir = self.step_dir(step)
        # drop the COMMIT marker first so a crash mid-rmtree leaves an
        # uncommitted (ignored) dir, not a corrupt "committed" one
        try:
            os.unlink(os.path.join(sdir, COMMIT_MARKER))
        except FileNotFoundError:
            pass
        shutil.rmtree(sdir, ignore_errors=True)

    def _apply_retention(self):
        steps = self.committed_steps()
        if not steps:
            return
        latest = steps[-1]
        keep = set()
        keep.add(latest)
        if self.num_to_keep is not None:
            keep.update(steps[-max(int(self.num_to_keep), 1):])
        else:
            keep.update(steps)
        if self.keep_every_k > 0:
            keep.update(s for s in steps if s % self.keep_every_k == 0)
        for s in steps:
            if s not in keep:
                self.delete_step(s)
        self._reap_dangling(latest)

    def _reap_dangling(self, latest_committed_step: int):
        """Remove abandoned tmp dirs and uncommitted step dirs that a
        newer committed step supersedes. In-flight saves are always for
        steps newer than the latest committed, so this never races a live
        writer."""
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return
        for name in names:
            tstep = self._parse_step(name, _TMP_PREFIX)
            if tstep is not None and tstep <= latest_committed_step:
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
                continue
            sstep = self._parse_step(name)
            if (sstep is not None and sstep < latest_committed_step
                    and not self.is_committed(sstep)):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    def __repr__(self):
        return (f"CheckpointManager(root={self.root!r}, "
                f"num_to_keep={self.num_to_keep}, "
                f"keep_every_k={self.keep_every_k})")
