"""ray_tpu.checkpoint — the durable checkpoint engine.

A step-numbered checkpoint root with atomic commit (write to
``tmp_step_N/``, per-file checksums in the manifest, fsync, rename +
``COMMIT`` marker), retention driven by ``air.config.CheckpointConfig``,
and async sharded saves that block the train step only for the host
snapshot. See docs/CHECKPOINTING.md for the layout and commit protocol.

No reference analogue in the seed (python/ray checkpointing is
storage-backend glue); the save path is orbax-style: every process
writes only the shards it owns, a single committer seals the step.
"""

from ray_tpu.checkpoint.manager import (  # noqa: F401
    COMMIT_MARKER, MANIFEST_NAME, CheckpointManager, PendingCheckpoint)
from ray_tpu.checkpoint.async_checkpointer import (  # noqa: F401
    AsyncCheckpointer, SaveStats, snapshot_to_host)

__all__ = [
    "CheckpointManager", "AsyncCheckpointer", "PendingCheckpoint",
    "SaveStats", "snapshot_to_host", "COMMIT_MARKER", "MANIFEST_NAME",
]
