"""``ray-tpu lint`` / ``python -m ray_tpu.analysis``.

Exit codes: 0 clean (baselined findings allowed), 1 unsuppressed
findings or stale baseline entries, 2 usage error. The tier-1 gate
(`tests/test_static_analysis.py`) runs the same code path in-process.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from ray_tpu.analysis import baseline as baseline_mod
from ray_tpu.analysis import reporter
from ray_tpu.analysis.core import analyze_paths, iter_py_files, registry

DEFAULT_EXCLUDES = ["__pycache__", "/generated/", "_pb2.py"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ray-tpu lint",
        description=("rtpulint: project-aware static analysis — "
                     "enforces ray_tpu's concurrency, resource and "
                     "wire-protocol invariants (see docs/"
                     "STATIC_ANALYSIS.md)"))
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to lint (default: the ray_tpu "
                        "package next to this install)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--select", metavar="CODES",
                   help="comma list of checker codes to run "
                        "(default: all)")
    p.add_argument("--baseline", metavar="PATH", default=None,
                   help="baseline file (default: nearest "
                        f"{baseline_mod.DEFAULT_BASENAME} above the "
                        "first path)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings as the new baseline "
                        "(entries still need hand-written "
                        "justifications before the gate accepts them)")
    p.add_argument("--exclude", action="append", default=None,
                   metavar="SUBSTR",
                   help="path substrings to skip (repeatable; default "
                        f"{DEFAULT_EXCLUDES})")
    p.add_argument("--list-checkers", action="store_true",
                   help="print the checker catalog and exit")
    p.add_argument("--gen-docs", action="store_true",
                   help="regenerate docs/CONFIGURATION.md and the "
                        "chaos-site table in docs/FAULT_TOLERANCE.md")
    p.add_argument("--check-docs", action="store_true",
                   help="like --gen-docs but fail (exit 1) instead of "
                        "writing when the committed docs are stale")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also print baselined findings")
    return p


def _default_paths() -> List[str]:
    import ray_tpu
    return [os.path.dirname(os.path.abspath(ray_tpu.__file__))]


def _repo_root(paths: List[str]) -> str:
    root = os.path.abspath(paths[0])
    if os.path.isfile(root):
        root = os.path.dirname(root)
    # the package dir's parent is the repo root when linting ray_tpu/
    if os.path.basename(root) == "ray_tpu":
        return os.path.dirname(root)
    return root


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.list_checkers:
        for code, cls in registry().items():
            print(f"{code}  {cls.name:28s} {cls.description}")
        return 0

    paths = args.paths or _default_paths()

    if args.gen_docs or args.check_docs:
        from ray_tpu.analysis.docs_gen import generate_all
        results = generate_all(_repo_root(paths),
                               write=not args.check_docs)
        stale = [p for p, (_, changed) in results.items() if changed]
        for p in sorted(results):
            _, changed = results[p]
            state = ("STALE" if args.check_docs else "regenerated") \
                if changed else "up to date"
            print(f"{p}: {state}")
        return 1 if (args.check_docs and stale) else 0

    select = [c.strip() for c in args.select.split(",")] \
        if args.select else None
    excludes = args.exclude if args.exclude is not None \
        else list(DEFAULT_EXCLUDES)

    files = list(iter_py_files(paths, exclude=excludes))
    try:
        findings = analyze_paths(paths, select=select, exclude=excludes)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    bl_path = None
    if not args.no_baseline:
        bl_path = args.baseline or baseline_mod.default_path(paths[0])

    if args.write_baseline:
        target = bl_path or os.path.join(
            _repo_root(paths), baseline_mod.DEFAULT_BASENAME)
        baseline_mod.save(target, findings)
        print(f"wrote {len(findings)} entr(y/ies) to {target} — add a "
              f"justification comment to each before committing")
        return 0

    entries = []
    if bl_path and os.path.isfile(bl_path):
        try:
            entries = baseline_mod.load(bl_path)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    unsuppressed, baselined, stale = baseline_mod.apply(findings,
                                                        entries)

    if args.as_json:
        print(reporter.render_json(unsuppressed, baselined, stale,
                                   files_scanned=len(files)))
    else:
        print(reporter.render_text(unsuppressed, baselined, stale,
                                   files_scanned=len(files),
                                   verbose=args.verbose))
    return 1 if (unsuppressed or stale) else 0


if __name__ == "__main__":
    sys.exit(main())
