"""rtpulint core: the project-aware static analysis framework.

The substrate mixes asyncio loops, an epoll reactor, plain threads and
refcounted shared pages — and its bug history (the rtpu-data-prefetch
thread leak, the ``ReplicaSet.assign`` lock race, the tracing-flusher
daemon-thread leak, unbounded ``pending_tasks`` growth, KV-page refcount
pairing) is a catalog of *invariant* violations, not logic errors.
Generic linters cannot see those invariants; this framework encodes
them as AST checkers that run over the tree in tier-1, so the next
violation fails a test instead of a game day.

Architecture (stdlib ``ast`` only — no new dependencies):

* :class:`Checker` subclasses declare a ``code`` (``RTPU0xx``) and
  implement ``check_module(ctx)``; the ``@register`` decorator adds
  them to the global registry.
* :class:`ModuleContext` wraps one parsed file: source, AST, a
  node→enclosing-scope map, per-line pragma suppressions, and a
  ``config`` dict checkers read overrides from (tests inject fixture
  registries there; production runs use the live ones).
* ``analyze_paths()`` walks ``*.py`` files (skipping ``__pycache__``
  and generated code), runs every registered checker, and filters
  findings through inline pragmas:

      something_suspicious()  # rtpulint: ignore[RTPU002]
      # rtpulint: ignore[RTPU001,RTPU003]   <- bare line: covers next line
      anything_goes()         # rtpulint: ignore

* Grandfathered findings live in a reviewed baseline file
  (:mod:`ray_tpu.analysis.baseline`); everything else fails the
  tier-1 gate (``tests/test_static_analysis.py``).

See docs/STATIC_ANALYSIS.md for the workflow and checker catalog.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Type

__all__ = [
    "Finding", "Checker", "ModuleContext", "register", "registry",
    "analyze_source", "analyze_file", "analyze_paths", "iter_py_files",
]

_PRAGMA_RE = re.compile(
    r"#\s*rtpulint:\s*ignore(?:\[(?P<codes>[A-Z0-9_,\s]+)\])?")

# directories never scanned (relative path components)
_SKIP_DIRS = {"__pycache__", ".git", ".eggs", "build", "dist"}


@dataclasses.dataclass
class Finding:
    """One checker hit. ``scope`` is the dotted enclosing-definition
    chain (``Class.method`` or ``<module>``) — it feeds the baseline
    fingerprint so unrelated edits moving line numbers don't churn the
    baseline."""

    code: str
    message: str
    path: str          # as given to the analyzer
    relpath: str       # relative to the scan root (fingerprint key)
    line: int
    col: int = 0
    scope: str = "<module>"

    def fingerprint(self) -> str:
        h = hashlib.sha1(
            f"{self.code}|{self.relpath}|{self.scope}|{self.message}"
            .encode()).hexdigest()[:12]
        return h

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.code} "
                f"[{self.scope}] {self.message}")

    def as_dict(self) -> Dict[str, Any]:
        return {"code": self.code, "message": self.message,
                "path": self.path, "relpath": self.relpath,
                "line": self.line, "col": self.col, "scope": self.scope,
                "fingerprint": self.fingerprint()}


class ModuleContext:
    """Everything a checker needs about one parsed module."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module, config: Optional[Dict[str, Any]] = None):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.config: Dict[str, Any] = config or {}
        self._scopes: Dict[int, str] = {}
        self._parents: Dict[int, ast.AST] = {}
        self._build_maps()

    def _build_maps(self) -> None:
        def walk(node: ast.AST, scope: str, parent: Optional[ast.AST]):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node
                child_scope = scope
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    child_scope = (child.name if scope == "<module>"
                                   else f"{scope}.{child.name}")
                    self._scopes[id(child)] = child_scope
                else:
                    self._scopes[id(child)] = scope
                walk(child, child_scope, child)
        self._scopes[id(self.tree)] = "<module>"
        walk(self.tree, "<module>", None)

    def scope(self, node: ast.AST) -> str:
        """Enclosing dotted definition chain for ``node`` (the node's
        own name if it *is* a def/class)."""
        return self._scopes.get(id(node), "<module>")

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def finding(self, code: str, node: ast.AST, message: str) -> Finding:
        return Finding(code=code, message=message, path=self.path,
                       relpath=self.relpath,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       scope=self.scope(node))

    # ------------------------------------------------------------- pragmas

    def suppressed_codes(self, line: int) -> Optional[Set[str]]:
        """Codes suppressed at ``line`` (empty set = all codes), or
        None when no pragma applies. A pragma on its own line covers
        the next source line."""
        cache = getattr(self, "_pragma_cache", None)
        if cache is None:
            cache = self._pragma_cache = self._parse_pragmas()
        return cache.get(line)

    def _parse_pragmas(self) -> Dict[int, Set[str]]:
        out: Dict[int, Set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            codes: Set[str] = set()
            if m.group("codes"):
                codes = {c.strip() for c in m.group("codes").split(",")
                         if c.strip()}
            target = i
            if text[:m.start()].strip() == "":
                target = i + 1  # bare pragma line covers the next line
            prev = out.get(target)
            if prev is not None:
                # merging an ignore-all (empty set) with a code list
                # keeps ignore-all
                codes = set() if (not prev or not codes) else prev | codes
            out[target] = codes
        return out

    def is_suppressed(self, finding: Finding) -> bool:
        codes = self.suppressed_codes(finding.line)
        if codes is None:
            return False
        return not codes or finding.code in codes


class Checker:
    """Base class. Subclasses set ``code``/``name``/``description`` and
    implement :meth:`check_module`."""

    code: str = "RTPU000"
    name: str = "base"
    description: str = ""

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    if cls.code in _REGISTRY:
        raise ValueError(f"duplicate checker code {cls.code}")
    _REGISTRY[cls.code] = cls
    return cls


def registry() -> Dict[str, Type[Checker]]:
    """code -> Checker class, with the default checker set loaded."""
    # importing the package registers every built-in checker
    from ray_tpu.analysis import checkers  # noqa: F401
    return dict(sorted(_REGISTRY.items()))


def _instantiate(select: Optional[Iterable[str]] = None) -> List[Checker]:
    reg = registry()
    if select:
        sel = set(select)
        unknown = sel - set(reg)
        if unknown:
            raise ValueError(f"unknown checker codes: {sorted(unknown)}")
        reg = {c: k for c, k in reg.items() if c in sel}
    return [cls() for cls in reg.values()]


# --------------------------------------------------------------- execution

def analyze_source(source: str, path: str = "<string>",
                   relpath: Optional[str] = None,
                   config: Optional[Dict[str, Any]] = None,
                   select: Optional[Iterable[str]] = None,
                   respect_pragmas: bool = True) -> List[Finding]:
    """Run checkers over one source string (fixture-test entrypoint)."""
    tree = ast.parse(source, filename=path)
    ctx = ModuleContext(path, relpath or path, source, tree, config)
    out: List[Finding] = []
    for checker in _instantiate(select):
        for f in checker.check_module(ctx):
            if respect_pragmas and ctx.is_suppressed(f):
                continue
            out.append(f)
    out.sort(key=lambda f: (f.relpath, f.line, f.code))
    return out


def analyze_file(path: str, root: Optional[str] = None,
                 config: Optional[Dict[str, Any]] = None,
                 select: Optional[Iterable[str]] = None) -> List[Finding]:
    with open(path, encoding="utf-8", errors="replace") as fh:
        source = fh.read()
    relpath = os.path.relpath(path, root) if root else path
    try:
        return analyze_source(source, path=path, relpath=relpath,
                              config=config, select=select)
    except SyntaxError as e:
        return [Finding(code="RTPU000",
                        message=f"syntax error: {e.msg}",
                        path=path, relpath=relpath.replace(os.sep, "/"),
                        line=e.lineno or 1, col=e.offset or 0)]


def iter_py_files(paths: Iterable[str],
                  exclude: Optional[Iterable[str]] = None
                  ) -> Iterable[str]:
    """Yield ``*.py`` files under ``paths`` (files pass through),
    skipping ``__pycache__``-style dirs and ``exclude`` substrings."""
    excludes = list(exclude or [])

    def skip(p: str) -> bool:
        q = p.replace(os.sep, "/")
        return any(x in q for x in excludes)

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and not skip(p):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    if not skip(full):
                        yield full


def analyze_paths(paths: Iterable[str], root: Optional[str] = None,
                  config: Optional[Dict[str, Any]] = None,
                  select: Optional[Iterable[str]] = None,
                  exclude: Optional[Iterable[str]] = None,
                  on_file: Optional[Callable[[str], None]] = None
                  ) -> List[Finding]:
    """Analyze every python file under ``paths``. ``root`` anchors the
    relative paths used by baseline fingerprints (defaults to the
    common parent of ``paths``)."""
    paths = list(paths)
    if root is None:
        root = os.path.commonpath([os.path.abspath(p) for p in paths]) \
            if paths else os.getcwd()
        if os.path.isfile(root):
            root = os.path.dirname(root)
    out: List[Finding] = []
    for fp in iter_py_files(paths, exclude=exclude):
        if on_file:
            on_file(fp)
        out.extend(analyze_file(fp, root=root, config=config,
                                select=select))
    out.sort(key=lambda f: (f.relpath, f.line, f.code))
    return out


# ----------------------------------------------------------- AST helpers
# shared by checkers; kept here so every checker resolves names the
# same way

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def walk_no_nested_defs(node: ast.AST, *, skip_async: bool = True,
                        skip_sync: bool = True) -> Iterable[ast.AST]:
    """Yield descendants of ``node`` without entering nested function
    definitions (their bodies run in their own context, not the
    enclosing one). ``node`` itself is not yielded."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, ast.AsyncFunctionDef) and skip_async:
            continue
        if isinstance(cur, (ast.FunctionDef, ast.Lambda)) and skip_sync:
            continue
        stack.extend(ast.iter_child_nodes(cur))


def module_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments (used to resolve
    constants passed where a checker wants a literal)."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            val = const_str(stmt.value)
            if val is not None:
                out[stmt.targets[0].id] = val
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name) \
                and stmt.value is not None:
            val = const_str(stmt.value)
            if val is not None:
                out[stmt.target.id] = val
    return out
