"""Reviewed baseline of grandfathered rtpulint findings.

A baseline entry acknowledges a finding without fixing it — every
entry needs a justification comment, and the tier-1 gate fails if the
file grows stale entries (finding fixed but entry kept) so the list
only shrinks. Format, one finding per line::

    RTPU003 ray_tpu/foo/bar.py Class.method 1a2b3c4d5e6f  # why it's ok

The fingerprint hashes (code, relpath, enclosing scope, message) — not
the line number — so unrelated edits that move code don't churn the
baseline, while any change to the finding itself invalidates the entry
for re-review.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

from ray_tpu.analysis.core import Finding

__all__ = ["BaselineEntry", "load", "save", "apply", "default_path",
           "format_entry", "DEFAULT_BASENAME"]

DEFAULT_BASENAME = ".rtpulint-baseline"

_LINE_RE = re.compile(
    r"^(?P<code>RTPU\d{3})\s+(?P<relpath>\S+)\s+(?P<scope>\S+)\s+"
    r"(?P<fp>[0-9a-f]{12})\s*(?:#\s*(?P<comment>.*))?$")


class BaselineEntry:
    __slots__ = ("code", "relpath", "scope", "fingerprint", "comment",
                 "lineno")

    def __init__(self, code: str, relpath: str, scope: str,
                 fingerprint: str, comment: str = "", lineno: int = 0):
        self.code = code
        self.relpath = relpath
        self.scope = scope
        self.fingerprint = fingerprint
        self.comment = comment
        self.lineno = lineno

    def key(self) -> Tuple[str, str]:
        return (self.code, self.fingerprint)


def default_path(start: str) -> Optional[str]:
    """Walk up from ``start`` looking for the baseline file."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        cand = os.path.join(cur, DEFAULT_BASENAME)
        if os.path.isfile(cand):
            return cand
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt


def load(path: str) -> List[BaselineEntry]:
    entries: List[BaselineEntry] = []
    with open(path, encoding="utf-8") as fh:
        for i, raw in enumerate(fh, start=1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            m = _LINE_RE.match(line)
            if not m:
                raise ValueError(
                    f"{path}:{i}: malformed baseline line: {line!r}")
            if not m.group("comment"):
                raise ValueError(
                    f"{path}:{i}: baseline entry needs a justification "
                    f"comment: {line!r}")
            entries.append(BaselineEntry(
                m.group("code"), m.group("relpath"), m.group("scope"),
                m.group("fp"), (m.group("comment") or "").strip(), i))
    return entries


def format_entry(f: Finding, comment: str = "TODO: justify") -> str:
    return (f"{f.code} {f.relpath} {f.scope} {f.fingerprint()}"
            f"  # {comment}")


def save(path: str, findings: Iterable[Finding],
         header: Optional[str] = None) -> None:
    lines = [header.rstrip() if header else
             "# rtpulint baseline — reviewed, grandfathered findings.\n"
             "# One per line: CODE relpath scope fingerprint  # why"]
    for f in sorted(findings, key=lambda f: (f.relpath, f.line, f.code)):
        lines.append(format_entry(f))
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")


def apply(findings: List[Finding], entries: List[BaselineEntry]
          ) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
    """Split ``findings`` against the baseline.

    Returns ``(unsuppressed, baselined, stale_entries)`` — stale
    entries match no live finding and must be deleted (the gate fails
    on them: a baseline may only shrink)."""
    by_key: Dict[Tuple[str, str], BaselineEntry] = {
        e.key(): e for e in entries}
    matched: set = set()
    unsuppressed: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        key = (f.code, f.fingerprint())
        if key in by_key:
            matched.add(key)
            baselined.append(f)
        else:
            unsuppressed.append(f)
    stale = [e for e in entries if e.key() not in matched]
    return unsuppressed, baselined, stale
