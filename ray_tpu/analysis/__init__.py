"""rtpulint — project-aware static analysis for ray_tpu.

Enforces the concurrency, resource and wire-protocol invariants the
substrate's bug history keeps re-teaching (blocking calls on event
loops, locks across ``await``, unpaired incref/decref and daemon
threads, undeclared chaos sites, unregistered ``RTPU_*`` knobs,
unguarded version-gated wire fields, silent swallows in control
loops). Runs in tier-1 over the whole tree; see
docs/STATIC_ANALYSIS.md.

    ray-tpu lint [--json] [paths...]
    python -m ray_tpu.analysis --list-checkers

Public surface: :func:`analyze_paths` / :func:`analyze_source` for
programmatic runs, :class:`Finding`, :func:`registry`, and the
:mod:`~ray_tpu.analysis.baseline` helpers.
"""

from ray_tpu.analysis.core import (Checker, Finding, ModuleContext,
                                   analyze_file, analyze_paths,
                                   analyze_source, register, registry)

__all__ = ["Checker", "Finding", "ModuleContext", "analyze_file",
           "analyze_paths", "analyze_source", "register", "registry"]
