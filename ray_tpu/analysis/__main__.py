"""``python -m ray_tpu.analysis`` — same surface as ``ray-tpu lint``."""

import sys

from ray_tpu.analysis.cli import main

sys.exit(main())
